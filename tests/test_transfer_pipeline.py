"""ISSUE 6: the double-buffered transfer pipeline and the universal
raw device lane.

Covers: depth-1 vs depth-2 byte-identity (+ the exact in-flight
bound), raw-vs-decoded digit-identity for every newly supported DATA
sample type (u8, signed byte, float32) and multi-pol state (4-pol
IQUV, AA+BB), the h2d_start/h2d_done telemetry schema and pptrace's
link section, the PPT_PIPELINE_DEPTH / PPT_COMPILE_CACHE env hooks,
and the persistent compilation cache wiring.  All shapes tiny
(nchan <= 16, nbin <= 256) per the tier-1 budget."""

import io
import os

import numpy as np
import pytest

from pulseportraiture_tpu import config, telemetry
from pulseportraiture_tpu.pipeline import stream as S

from fits_forge import forge_archive, gaussian_portrait


def _noisy_maker(nchan, nbin, nsub, npol, seed=3, sigma=0.08):
    """Gaussian portrait + per-(subint, pol) noise: a noiseless forge
    makes chi2 astronomically conditioned (data == template exactly),
    where host-FFT-vs-device-DFT rounding at 1e-16 shows in the 11th
    digit of the -snr flag; realistic noise is what the lanes meet."""
    base = gaussian_portrait(nchan, nbin)
    rng = np.random.default_rng(seed)
    noise = {(s, p): rng.normal(0.0, sigma, (nchan, nbin))
             for s in range(nsub) for p in range(npol)}
    return lambda s, p: base * (1.0 + 0.1 * p) + 0.1 * s + noise[(s, p)]


def _forge_and_template(tmp_path, name, **kw):
    """Forge one noisy archive + a template built from its scrunch."""
    from pulseportraiture_tpu.io.psrfits import (read_archive,
                                                 unload_new_archive)

    nsub, nchan, nbin = 2, 8, 128
    npol = kw.get("npol", 1)
    f = str(tmp_path / f"{name}.fits")
    forge_archive(f, nsub=nsub, nchan=nchan, nbin=nbin, dedisp=0,
                  data_maker=_noisy_maker(nchan, nbin, nsub, npol),
                  **kw)
    arch = read_archive(f)
    arch.tscrunch()
    tmpl = str(tmp_path / f"{name}_tmpl.fits")
    unload_new_archive(np.asarray(arch.amps), arch, tmpl, DM=0.0,
                      dmc=1, quiet=True)
    return f, tmpl


# ---------------------------------------------------------------------------
# universal raw lane: every sample type / pol state, digit-identical
# ---------------------------------------------------------------------------

RAW_CASES = {
    # name -> (forge kwargs, expected raw_code, expected pol_sum)
    "u8": (dict(data_dtype="u1"), "u8", False),
    "i8": (dict(data_dtype="i1"), "i8", False),
    "f32be": (dict(data_dtype=">f4"), "f32", False),
    "iquv4": (dict(data_dtype=">i2", npol=4, pol_type="IQUV"),
              "i16", False),
    "aabb": (dict(data_dtype=">i2", npol=2, pol_type="AA+BB"),
             "i16", True),
    # ISSUE 15: sub-byte packed payloads ship PACKED and unpack on
    # device; general TSCAL/TZERO ships its scalars
    "nbit2": (dict(data_dtype="nbit2"), "p2", False),
    "nbit4": (dict(data_dtype="nbit4"), "p4", False),
    "nbit2_aabb": (dict(data_dtype="nbit2", npol=2,
                        pol_type="AA+BB"), "p2", True),
    "tscal_i16": (dict(data_dtype=">i2", data_tscal=0.5,
                       data_tzero=2.0), "i16", False),
    "tscal_u8": (dict(data_dtype="u1", data_tscal=0.25,
                      data_tzero=-3.0), "u8", False),
}


@pytest.mark.parametrize("case", sorted(RAW_CASES))
def test_raw_lane_universal_digit_identical(case, tmp_path,
                                            monkeypatch):
    """The raw device lane must (a) actually engage for the new
    sample types / pol states and (b) produce .tim output
    digit-identical to the decoded host lane (the oracle)."""
    kw, want_code, want_sum = RAW_CASES[case]
    f, tmpl = _forge_and_template(tmp_path, case, **kw)

    d = S._load_raw(f)
    assert d.raw_code == want_code
    assert d.pol_sum is want_sum
    if want_sum:
        assert d.raw.shape[1] == 2  # two summand pols ship
    if "data_tscal" in kw:
        assert d.tscal == kw["data_tscal"]
        assert d.tzero == kw["data_tzero"]

    tim_raw = str(tmp_path / "raw.tim")
    r1 = S.stream_wideband_TOAs([f], tmpl, nsub_batch=4, quiet=True,
                                tim_out=tim_raw)
    assert len(r1.TOA_list) == 2
    assert r1.h2d_bytes > 0

    # force the decoded fallback lane (the digit-exactness oracle)
    def refuse(path):
        raise ValueError("forced decode for the oracle arm")

    monkeypatch.setattr(S, "_load_raw", refuse)
    tim_dec = str(tmp_path / "dec.tim")
    r2 = S.stream_wideband_TOAs([f], tmpl, nsub_batch=4, quiet=True,
                                tim_out=tim_dec)
    assert len(r2.TOA_list) == 2
    assert open(tim_raw).read() == open(tim_dec).read()


def test_raw_narrowband_packed_digit_identical(tmp_path, monkeypatch):
    """The NARROWBAND streaming lane's raw path must engage for a
    packed archive and match its decoded-fallback oracle per channel
    (the 'both streaming lanes' digit gate)."""
    f, tmpl = _forge_and_template(tmp_path, "nbpacked",
                                  data_dtype="nbit4")
    tim_raw = str(tmp_path / "nb_raw.tim")
    r1 = S.stream_narrowband_TOAs([f], tmpl, nsub_batch=4, quiet=True,
                                  tim_out=tim_raw)
    assert len(r1.TOA_list) > 0

    def refuse(path):
        raise ValueError("forced decode for the oracle arm")

    monkeypatch.setattr(S, "_load_raw", refuse)
    tim_dec = str(tmp_path / "nb_dec.tim")
    r2 = S.stream_narrowband_TOAs([f], tmpl, nsub_batch=4, quiet=True,
                                  tim_out=tim_dec)
    assert len(r2.TOA_list) == len(r1.TOA_list)
    assert open(tim_raw).read() == open(tim_dec).read()


def test_raw_subbyte_byte_reduction(tmp_path, monkeypatch):
    """A 2-bit corpus must ship MUCH less than its decoded-f64
    fallback — >= 8x at a padded bucket shape (the acceptance gate;
    the full-size claim rides bench_campaign's tunnel-emu arm)."""
    f, tmpl = _forge_and_template(tmp_path, "ratio2",
                                  data_dtype="nbit2")
    # nsub_batch 64 pads the dispatch like a campaign bucket, so the
    # payload (not the shared model/mask args) dominates both lanes
    r1 = S.stream_wideband_TOAs([f], tmpl, nsub_batch=64, quiet=True)
    monkeypatch.setattr(config, "raw_subbyte", False)
    with pytest.raises(ValueError):
        S._load_raw(f)  # the escape hatch forces the decoded lane
    r2 = S.stream_wideband_TOAs([f], tmpl, nsub_batch=64, quiet=True)
    assert [t.MJD.tim_string() for t in r1.TOA_list] == \
        [t.MJD.tim_string() for t in r2.TOA_list]
    assert r2.h2d_bytes / r1.h2d_bytes >= 8.0


def test_raw_refuses_unrepresentable_layouts(tmp_path, monkeypatch):
    """Layouts raw mode still cannot represent keep refusing loudly
    (the loader then falls back to the decoded lane): packed +
    FITS-scaled columns, misaligned sub-byte pol planes, and the
    PPT_RAW_SUBBYTE escape hatch."""
    nchan, nbin = 8, 64
    ok = str(tmp_path / "nbit4_ok.fits")
    forge_archive(ok, nsub=1, nchan=nchan, nbin=nbin,
                  data_dtype="nbit4")
    assert S._load_raw(ok).raw_code == "p4"  # engages by default
    monkeypatch.setattr(config, "raw_subbyte", False)
    with pytest.raises(ValueError):
        S._load_raw(ok)
    monkeypatch.setattr(config, "raw_subbyte", True)
    # a 2-bit plane of 30 samples does not byte-align (30*2 % 8 != 0)
    mis = str(tmp_path / "nbit2_misaligned.fits")
    forge_archive(mis, nsub=1, nchan=5, nbin=6, data_dtype="nbit2")
    with pytest.raises(ValueError):
        S._load_raw(mis)
    # packed payloads cannot channel-pad (config.bucket_pad)
    monkeypatch.setattr(config, "bucket_pad", True)
    forge_archive(str(tmp_path / "nbit4_pad.fits"), nsub=1, nchan=6,
                  nbin=64, data_dtype="nbit4")
    with pytest.raises(ValueError):
        S._load_raw(str(tmp_path / "nbit4_pad.fits"))


# ---------------------------------------------------------------------------
# the transfer pipeline: depth A/B, exact bound, telemetry
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def pipeline_corpus(tmp_path_factory):
    """Three tiny int16 archives + template, shared by the depth A/B
    and telemetry tests."""
    from pulseportraiture_tpu.io import write_gmodel
    from pulseportraiture_tpu.synth import (default_test_model,
                                            make_fake_pulsar)
    from pulseportraiture_tpu.utils.mjd import MJD

    tmp = tmp_path_factory.mktemp("tpipe")
    model = default_test_model(1500.0)
    gmodel = str(tmp / "m.gmodel")
    write_gmodel(model, gmodel, quiet=True)
    files = []
    for i in range(3):
        p = str(tmp / f"a{i}.fits")
        make_fake_pulsar(model, {"PSR": "TP", "P0": 0.003, "DM": 10.0,
                                 "PEPOCH": 55000.0},
                         outfile=p, nsub=2, nchan=16, nbin=128,
                         dDM=2e-4 * i, start_MJD=MJD(55100 + i, 0.1),
                         noise_stds=0.05, dedispersed=False,
                         quiet=True, rng=i)
        files.append(p)
    return tmp, files, gmodel


@pytest.mark.slow  # ~16 s; the depth-1-vs-N byte identity is gated
# in-bench every bench_campaign run, and test_h2d_telemetry_schema_and
# _report keeps the pipelined lane's schema tier-1
def test_pipeline_depth_byte_identical_and_bounded(pipeline_corpus):
    """depth=1 (serialized copy/fit, the pre-pipeline arm) and
    depth=2 (double-buffered) must produce byte-identical .tim and
    TOA fields, and the exact per-device in-flight bound must hold
    with the pipeline in front of it."""
    tmp, files, gmodel = pipeline_corpus
    outs = {}
    for depth in (1, 2):
        tim = str(tmp / f"d{depth}.tim")
        res = S.stream_wideband_TOAs(
            files, gmodel, nsub_batch=2, quiet=True, tim_out=tim,
            pipeline_depth=depth, max_inflight=2)
        assert res.peak_inflight <= 2
        assert res.h2d_bytes > 0 and res.h2d_duration >= 0.0
        outs[depth] = (open(tim).read(),
                       [(t.MJD.tim_string(), t.TOA_error, dict(t.flags))
                        for t in res.TOA_list])
    assert outs[1] == outs[2]


def test_h2d_telemetry_schema_and_report(pipeline_corpus):
    """A traced pipelined run emits schema-valid h2d_start/h2d_done
    pairs (one per dispatch, keyed by seq, byte counts positive) and
    pptrace's link section aggregates them."""
    tmp, files, gmodel = pipeline_corpus
    trace = str(tmp / "trace.jsonl")
    res = S.stream_wideband_TOAs(files, gmodel, nsub_batch=2,
                                 quiet=True, telemetry=trace,
                                 pipeline_depth=2)
    manifest, events = telemetry.validate_trace(trace)
    assert manifest["config"]["stream_pipeline_depth"] == \
        config.stream_pipeline_depth
    starts = {e["seq"]: e for e in events if e["type"] == "h2d_start"}
    dones = {e["seq"]: e for e in events if e["type"] == "h2d_done"}
    dispatches = {e["seq"] for e in events if e["type"] == "dispatch"}
    assert len(dones) == res.nfit
    assert set(starts) == set(dones) == dispatches
    assert sum(e["bytes"] for e in dones.values()) == res.h2d_bytes
    for seq, e in dones.items():
        assert e["bytes"] > 0 and e["h2d_s"] >= 0.0
        assert isinstance(e["overlap"], bool)
        assert starts[seq]["device"] == e["device"]
    run_end = [e for e in events if e["type"] == "run_end"][-1]
    assert run_end["h2d_bytes"] == res.h2d_bytes
    assert run_end["pipeline_depth"] == 2

    summary = telemetry.report(trace, file=io.StringIO())
    assert summary["n_h2d"] == res.nfit
    assert summary["h2d_bytes"] == res.h2d_bytes
    assert summary["h2d_s"] >= 0.0
    sf = summary["h2d_stall_frac"]
    assert sf is None or 0.0 <= sf <= 1.0


def test_report_tolerates_pre_pipeline_traces(tmp_path):
    """Traces written before the transfer pipeline (no h2d events)
    must still report — the link section just says so."""
    trace = str(tmp_path / "old.jsonl")
    tr = telemetry.Tracer(trace, run="old")
    tr.emit("run_end", driver="x", n_toas=0, nfit=0)
    tr.close()
    buf = io.StringIO()
    summary = telemetry.report(trace, file=buf)
    assert summary["n_h2d"] == 0
    assert summary["h2d_stall_frac"] is None
    assert "no h2d events" in buf.getvalue()


def test_pipeline_depth_config_and_env(monkeypatch):
    """config.stream_pipeline_depth default, the PPT_PIPELINE_DEPTH /
    PPT_COMPILE_CACHE env hooks, and their strict parses."""
    assert config.stream_pipeline_depth >= 1
    monkeypatch.setenv("PPT_PIPELINE_DEPTH", "3")
    monkeypatch.setenv("PPT_COMPILE_CACHE", "/tmp/ppt-cc-test")
    saved = (config.stream_pipeline_depth, config.compile_cache_dir)
    try:
        changed = config.env_overrides()
        assert "stream_pipeline_depth" in changed
        assert "compile_cache_dir" in changed
        assert config.stream_pipeline_depth == 3
        assert config.compile_cache_dir == "/tmp/ppt-cc-test"
        monkeypatch.setenv("PPT_COMPILE_CACHE", "off")
        config.env_overrides()
        assert config.compile_cache_dir is None
        monkeypatch.setenv("PPT_PIPELINE_DEPTH", "0")
        with pytest.raises(ValueError):
            config.env_overrides()
        monkeypatch.setenv("PPT_PIPELINE_DEPTH", "two")
        with pytest.raises(ValueError):
            config.env_overrides()
    finally:
        config.stream_pipeline_depth, config.compile_cache_dir = saved


def test_compile_cache_populates(tmp_path, monkeypatch):
    """enable_compile_cache routes jax's persistent cache to the
    configured directory and compiled programs land there (ROADMAP
    item 5 down payment — fleet restarts skip the recompile)."""
    import jax
    import jax.numpy as jnp

    from pulseportraiture_tpu.utils import device as D

    cache = str(tmp_path / "cc")
    monkeypatch.setattr(D, "_compile_cache_dir", None)
    monkeypatch.setattr(config, "compile_cache_dir", cache)
    try:
        assert D.enable_compile_cache() == cache
        fn = jax.jit(lambda x: jnp.cos(x) @ x.T * 2.0)
        jax.block_until_ready(fn(jnp.ones((32, 32))))
        assert os.listdir(cache), "no cache entries written"
        # idempotent re-apply
        assert D.enable_compile_cache() == cache
    finally:
        jax.config.update("jax_compilation_cache_dir", None)
        monkeypatch.setattr(D, "_compile_cache_dir", None)


def test_pptoas_pipeline_flags_validate():
    """--pipeline-depth / --transport-compress need --stream and sane
    values (cheap parse-level checks; the e2e plumbing rides
    test_cli's stream runs)."""
    from pulseportraiture_tpu.cli import pproute, pptoas

    with pytest.raises(SystemExit):
        pptoas.main(["-d", "x.fits", "-m", "m.gmodel",
                     "--pipeline-depth", "2"])
    with pytest.raises(SystemExit):
        pptoas.main(["-d", "x.fits", "-m", "m.gmodel", "--stream",
                     "--pipeline-depth", "0"])
    with pytest.raises(SystemExit):
        pptoas.main(["-d", "x.fits", "-m", "m.gmodel",
                     "--transport-compress", "auto"])  # needs --stream
    with pytest.raises(SystemExit):
        pptoas.main(["-d", "x.fits", "-m", "m.gmodel", "--stream",
                     "--transport-compress", "zlib"])
    saved = config.transport_compress
    try:
        with pytest.raises(SystemExit):
            pproute.main(["-r", "nope.jsonl",
                          "--transport-compress", "bad"])
    finally:
        config.transport_compress = saved


def test_ops_decode_units():
    """ops/decode: the signed-byte bias is removed exactly BEFORE
    scl/offs (bit-matching the host decode order), and pol_sum
    refuses payloads without a pol axis."""
    import jax.numpy as jnp

    from pulseportraiture_tpu.ops.decode import affine_decode

    raw = np.array([[[0, 128, 255, 7]]], np.uint8)  # (1, 1, 4)
    scl = np.array([[0.5]])
    offs = np.array([[1.0]])
    got = np.asarray(affine_decode(jnp.asarray(raw), jnp.asarray(scl),
                                   jnp.asarray(offs), jnp.float64,
                                   code="i8"))
    want = (raw.astype(np.float64) - 128.0) * 0.5 + 1.0
    assert np.array_equal(got, want)
    got_u8 = np.asarray(affine_decode(jnp.asarray(raw),
                                      jnp.asarray(scl),
                                      jnp.asarray(offs), jnp.float64,
                                      code="u8"))
    assert np.array_equal(got_u8, raw * 0.5 + 1.0)
    with pytest.raises(ValueError):
        affine_decode(jnp.asarray(raw), jnp.asarray(scl),
                      jnp.asarray(offs), jnp.float64, code="i4")

    # pol_sum: the two summand pols are baselined PER POL then summed
    # (host rm_baseline -> pscrunch order), and a payload without a
    # pol axis refuses
    from pulseportraiture_tpu.ops.decode import decode_stokes_I
    from pulseportraiture_tpu.ops.noise import min_window_baseline

    rng = np.random.default_rng(11)
    raw2 = rng.integers(0, 255, (1, 2, 3, 64)).astype(np.uint8)
    scl2 = np.ones((1, 2, 3))
    offs2 = np.zeros((1, 2, 3))
    got2 = np.asarray(decode_stokes_I(
        jnp.asarray(raw2), jnp.asarray(scl2), jnp.asarray(offs2),
        jnp.float64, code="u8", pol_sum=True))
    per_pol = raw2.astype(np.float64)
    per_pol = per_pol - np.asarray(
        min_window_baseline(jnp.asarray(per_pol)))[..., None]
    np.testing.assert_allclose(got2, per_pol[:, 0] + per_pol[:, 1],
                               rtol=0, atol=1e-12)
    with pytest.raises(ValueError):
        decode_stokes_I(jnp.asarray(raw2[:, 0]), jnp.asarray(scl2[:, 0]),
                        jnp.asarray(offs2[:, 0]), jnp.float64,
                        code="u8", pol_sum=True)


# ---------------------------------------------------------------------------
# ISSUE 15: sub-byte decode exactness, the transport codec, and the
# cost model
# ---------------------------------------------------------------------------

def _np_unpack(packed, nbit, nsamp):
    """Independent numpy reference for the MSB-first unpack."""
    per = 8 // nbit
    shifts = (np.arange(per - 1, -1, -1) * nbit).astype(np.uint8)
    v = (packed[..., :, None] >> shifts) & ((1 << nbit) - 1)
    return v.reshape(packed.shape[:-1] + (-1,))[..., :nsamp]


@pytest.mark.parametrize("nbit", [1, 2, 4])
@pytest.mark.parametrize("variant", ["plain", "datscl", "tscal"])
def test_unpack_bit_identity(nbit, variant):
    """Packed-vs-host-unpack bit identity across all three NBIT
    widths x {plain u8 interpretation, DAT_SCL/DAT_OFFS, general
    TSCAL/TZERO}: the device decode must reproduce the host decode
    EXACTLY (every value here is an exact f64)."""
    import jax.numpy as jnp

    from pulseportraiture_tpu.ops.decode import (decode_stokes_I,
                                                 unpack_bitplanes)
    from pulseportraiture_tpu.ops.noise import min_window_baseline

    rng = np.random.default_rng(nbit)
    nb, nchan, nbin = 2, 4, 64
    packed = rng.integers(0, 256, (nb, nchan * nbin * nbit // 8)) \
        .astype(np.uint8)
    want_samples = _np_unpack(packed, nbit, nchan * nbin) \
        .reshape(nb, nchan, nbin).astype(np.float64)
    got_samples = np.asarray(unpack_bitplanes(
        jnp.asarray(packed), nbit, nchan * nbin))
    assert np.array_equal(
        got_samples.reshape(nb, nchan, nbin), want_samples)

    scl = (np.ones((nb, nchan)) if variant == "plain"
           else rng.uniform(0.5, 2.0, (nb, nchan)))
    offs = (np.zeros((nb, nchan)) if variant == "plain"
            else rng.uniform(-1.0, 1.0, (nb, nchan)))
    tscal = tzero = None
    x = want_samples
    if variant == "tscal":
        tscal = np.full(nb, 0.25)
        tzero = np.full(nb, -3.0)
        x = x * tscal[:, None, None] + tzero[:, None, None]
    x = x * scl[..., None] + offs[..., None]
    want = x - np.asarray(
        min_window_baseline(jnp.asarray(x)))[..., None]
    got = np.asarray(decode_stokes_I(
        jnp.asarray(packed), jnp.asarray(scl), jnp.asarray(offs),
        jnp.float64, code=f"p{nbit}", nbin=nbin,
        tscal=None if tscal is None else jnp.asarray(tscal),
        tzero=None if tzero is None else jnp.asarray(tzero)))
    np.testing.assert_allclose(got, want, rtol=0, atol=1e-12)


def test_blockcodec_roundtrip_property():
    """Codec encode . decode round-trip property: random integer
    payloads across dtypes, spans, and row counts come back
    bit-identical, and incompressible payloads decline."""
    from pulseportraiture_tpu.io.blockcodec import (decode_rows,
                                                    encode_rows,
                                                    probe_width)

    rng = np.random.default_rng(7)
    for trial in range(20):
        nb = int(rng.integers(1, 5))
        nsamp = int(rng.integers(1, 8)) * 8
        dtype = rng.choice([np.uint8, np.int16])
        width_target = int(rng.choice([1, 2, 4, 8]))
        base = rng.integers(-200 if dtype == np.int16 else 0, 100,
                            nb)
        arr = (base[:, None]
               + rng.integers(0, 1 << width_target, (nb, nsamp))) \
            .astype(dtype)
        vmin, w = probe_width(arr)
        if dtype == np.uint8 and width_target == 8:
            assert w is None  # no width below the wire dtype
            continue
        assert w is not None and w <= width_target
        packed = encode_rows(arr, vmin, w)
        assert packed.nbytes < arr.nbytes
        back = decode_rows(packed, vmin, w, arr.shape, dtype)
        assert np.array_equal(back, arr)
    # full-range payloads are incompressible
    full = rng.integers(-30000, 30000, (2, 64)).astype(np.int16)
    assert probe_width(full) == (None, None)
    # float payloads are ineligible
    assert probe_width(full.astype(np.float32)) == (None, None)


def test_cost_model_never_engages_blind():
    """The cost model must never speculate: no link observation ->
    False; a fast (memcpy) link -> False; a slow (tunnel) link ->
    True for a worthwhile reduction."""
    from pulseportraiture_tpu.io.blockcodec import CostModel

    m = CostModel()
    assert not m.predict(1 << 20, 1 << 18)  # no link measured yet
    m.observe_link(1 << 20, 1e-4)  # ~10 GB/s memcpy-class link
    assert not m.predict(1 << 20, 1 << 18)
    m2 = CostModel()
    m2.observe_link(1 << 20, 0.5)  # ~2 MB/s tunnel-class link
    assert m2.predict(1 << 20, 1 << 18)
    # no saving -> never
    assert not m2.predict(1 << 20, 1 << 20)


def test_transport_compress_e2e(tmp_path, monkeypatch):
    """The h2d codec end to end on a coarsely-quantized byte corpus:
    'on' ships fewer bytes with digit-identical .tim; 'auto' on a
    bare-CPU link NEVER engages (the cost model predicts a loss); the
    telemetry ledger carries the decision trail."""
    from pulseportraiture_tpu.synth import (default_test_model,
                                            make_fake_pulsar)
    from pulseportraiture_tpu.io import write_gmodel
    from pulseportraiture_tpu.utils.mjd import MJD

    model = default_test_model(1500.0)
    gmodel = str(tmp_path / "m.gmodel")
    write_gmodel(model, gmodel, quiet=True)
    files = []
    for i in range(2):
        p = str(tmp_path / f"c{i}.fits")
        make_fake_pulsar(model, {"PSR": "TC", "P0": 0.003, "DM": 10.0,
                                 "PEPOCH": 55000.0},
                         outfile=p, nsub=2, nchan=16, nbin=128,
                         start_MJD=MJD(55100 + i, 0.1),
                         noise_stds=0.05, dedispersed=False,
                         quiet=True, rng=i, nbit=8, levels=4)
        files.append(p)
    tims, res = {}, {}
    for mode in (False, True, "auto"):
        monkeypatch.setattr(config, "transport_compress", mode)
        tim = str(tmp_path / f"tc_{mode}.tim")
        trace = str(tmp_path / f"tc_{mode}.jsonl")
        res[mode] = S.stream_wideband_TOAs(
            files, gmodel, nsub_batch=4, quiet=True, tim_out=tim,
            telemetry=trace)
        tims[mode] = open(tim).read()
    assert tims[False] == tims[True] == tims["auto"]
    assert res[True].h2d_bytes < res[False].h2d_bytes
    assert res[True].h2d_bytes_logical == res[False].h2d_bytes
    # 'auto' on a bare-CPU link: the first copy has no link estimate
    # and later ones predict a loss — zero engagement, ever
    assert res["auto"].h2d_bytes == res["auto"].h2d_bytes_logical
    # the decision ledger: every 'on' copy engaged, every 'auto' copy
    # declined on cost (or had no estimate)
    import io as _io

    summary = telemetry.report(str(tmp_path / "tc_True.jsonl"),
                               file=_io.StringIO())
    assert summary["codec_decisions"].get("engaged", 0) == \
        summary["n_h2d"]
    assert summary["h2d_bytes_logical"] > summary["h2d_bytes"]
    assert summary["h2d_compression"] > 1.0
    summary_auto = telemetry.report(str(tmp_path / "tc_auto.jsonl"),
                                    file=_io.StringIO())
    assert summary_auto["codec_decisions"].get("engaged", 0) == 0
    assert summary_auto["codec_decisions"].get("cost", 0) > 0
    assert summary_auto["h2d_bytes_logical"] == \
        summary_auto["h2d_bytes"]


def test_socket_frame_compression_roundtrip(monkeypatch):
    """Socket frames round-trip the zlib lane bit-exactly: a big
    compressible frame ships with the top-bit marker and decodes to
    the same object; small frames stay plain."""
    import socket as _socket
    import struct as _struct

    from pulseportraiture_tpu.serve import transport as T

    a, b = _socket.socketpair()
    try:
        # big enough to cross COMPRESS_MIN_FRAME, small enough that
        # the PLAIN send below fits the socketpair buffer (both ends
        # live on this one thread — a frame past the kernel buffer
        # would deadlock sendall against the unread peer)
        big = {"op": "result", "payload": ["x" * 64] * 1200}
        monkeypatch.setattr(config, "transport_compress", True)
        T._send_frame(a, big)
        # peek the length prefix: the marker bit must be set and the
        # wire body must be smaller than the JSON
        import json as _json

        body_len = len(_json.dumps(big,
                                   separators=(",", ":")).encode())
        head = T._recv_exact(b, 4)
        (n,) = _struct.unpack(">I", head)
        assert n & T._FRAME_ZLIB
        assert (n & ~T._FRAME_ZLIB) < body_len
        payload = T._recv_exact(b, n & ~T._FRAME_ZLIB)
        import zlib as _zlib

        assert _json.loads(_zlib.decompress(payload)) == big
        # and through the real receive path
        T._send_frame(a, big)
        assert T._recv_frame(b) == big
        # small frames stay plain even when compression is on
        T._send_frame(a, {"op": "stat"})
        head = T._recv_exact(b, 4)
        (n,) = _struct.unpack(">I", head)
        assert not n & T._FRAME_ZLIB
        _ = T._recv_exact(b, n)
        # off: byte-identical to prior releases
        monkeypatch.setattr(config, "transport_compress", False)
        T._send_frame(a, big)
        head = T._recv_exact(b, 4)
        (n,) = _struct.unpack(">I", head)
        assert not n & T._FRAME_ZLIB and n == body_len
        _ = T._recv_exact(b, n)
    finally:
        a.close()
        b.close()


def test_linkwar_env_knobs(monkeypatch):
    """PPT_RAW_SUBBYTE / PPT_TRANSPORT_COMPRESS: registered in
    KNOWN_PPT_ENV, strict parses, loud errors, snapshot in the
    telemetry manifest."""
    for name in ("PPT_RAW_SUBBYTE", "PPT_TRANSPORT_COMPRESS"):
        assert name in config.KNOWN_PPT_ENV
    for key in ("raw_subbyte", "transport_compress"):
        assert key in telemetry.CONFIG_SNAPSHOT_KEYS
    saved = (config.raw_subbyte, config.transport_compress)
    try:
        monkeypatch.setenv("PPT_RAW_SUBBYTE", "off")
        monkeypatch.setenv("PPT_TRANSPORT_COMPRESS", "auto")
        changed = config.env_overrides()
        assert "raw_subbyte" in changed
        assert "transport_compress" in changed
        assert config.raw_subbyte is False
        assert config.transport_compress == "auto"
        monkeypatch.setenv("PPT_RAW_SUBBYTE", "on")
        monkeypatch.setenv("PPT_TRANSPORT_COMPRESS", "on")
        config.env_overrides()
        assert config.raw_subbyte is True
        assert config.transport_compress is True
        monkeypatch.setenv("PPT_RAW_SUBBYTE", "maybe")
        with pytest.raises(ValueError):
            config.env_overrides()
        monkeypatch.setenv("PPT_RAW_SUBBYTE", "on")
        monkeypatch.setenv("PPT_TRANSPORT_COMPRESS", "sometimes")
        with pytest.raises(ValueError):
            config.env_overrides()
    finally:
        config.raw_subbyte, config.transport_compress = saved


def test_shape_key_roundtrip_new_tokens():
    """_bucket_shape <-> parse_shape_key stays an exact inverse for
    the new packed codes and the column-scaling token (the AOT warmup
    contract)."""
    for code in ("p1", "p2", "p4", "i16"):
        for col_scaled in (False, True):
            b = S._Bucket(np.linspace(1.0, 2.0, 8), 64, None,
                          (True, True, False, False, False),
                          kind="raw", raw_code=code,
                          col_scaled=col_scaled)
            spec = S.parse_shape_key(S._bucket_shape(b))
            assert spec["raw_code"] == code
            assert spec["col_scaled"] is col_scaled
            assert spec["nchan"] == 8 and spec["nbin"] == 64
