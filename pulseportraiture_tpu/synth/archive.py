"""Fake-pulsar PSRFITS generation — the de-facto end-to-end test
fixture (reference make_fake_pulsar, pplib.py:3302-3499, driven by
examples/example.py).

Same injection knobs as the reference: phase offset, dDM, DM(nu)
power-law terms, scattering, scintillation, per-channel scales/noise,
weights/RFI masks, dispersed or dedispersed output.  One deliberate
difference: the reference snapshot's phase/dDM injection line is
commented out (pplib.py:3463-3465), silently injecting nothing; here
the documented behavior — rotate by (phase, dDM) with dispersion
referenced to nu_DM (default: infinite frequency) — is implemented.

Host-side numpy: archive generation is a fixture/setup stage, not the
TPU hot path.
"""

import numpy as np

from ..config import Dconst, scattering_alpha
from ..io.gmodel import gen_gmodel_portrait, read_gmodel
from ..io.psrfits import new_archive, parse_parfile, rotate_phase
from ..utils.device import on_host
from ..utils.mjd import MJD


def add_scintillation(port, params=None, random=True, nsin=2, amax=1.0,
                      wmax=3.0, rng=None):
    """Multiply channels by a sum of sin^2 patterns (reference
    pplib.py:1190-1218).  params: flat triplets (amp, freq [cycles],
    phase [cycles]); otherwise ``nsin`` random sinusoids."""
    port = np.asarray(port, np.float64)
    nchan = port.shape[0]
    pattern = np.zeros(nchan)
    if params is None and not random:
        return port
    if params is not None:
        triplets = [params[i:i + 3] for i in range(0, len(params), 3)]
    else:
        rng = rng or np.random.default_rng()
        triplets = [(rng.uniform(0, amax), rng.chisquare(wmax),
                     rng.uniform(0, 1)) for _ in range(nsin)]
    for a, w, p in triplets:
        pattern += a * np.sin(np.linspace(0.0, w * np.pi, nchan)
                              + p * np.pi) ** 2.0
    return port * pattern[:, None]


def _dm_nu_delays(phase, dDM, P, freqs, xs, Cs, nu_DM):
    """Delays [rot] for the injected rotation: an achromatic phase
    plus either the standard nu^-2 dispersion of dDM or arbitrary
    power-law terms sum_i C_i*(nu^x_i - nu_DM^x_i)/P (reference
    add_DM_nu, pplib.py:2601-2638)."""
    freqs = np.asarray(freqs, np.float64)
    if xs is None:
        xs, Cs = [-2.0], [Dconst * dDM]
    delays = np.full(freqs.shape, float(phase))
    for x, C in zip(xs, Cs):
        ref_term = 0.0 if np.isinf(nu_DM) else float(nu_DM) ** x
        delays = delays + C * (freqs ** x - ref_term) / P
    return delays


@on_host
def make_fake_pulsar(modelfile, ephemeris, outfile="fake_pulsar.fits",
                     nsub=1, npol=1, nchan=512, nbin=2048, nu0=1500.0,
                     bw=800.0, tsub=300.0, phase=0.0, dDM=0.0,
                     start_MJD=None, weights=None, noise_stds=1.0,
                     scales=1.0, dedispersed=False, t_scat=0.0,
                     alpha=scattering_alpha, scint=False, xs=None, Cs=None,
                     nu_DM=np.inf, state="Stokes", telescope="GBT",
                     quiet=False, rng=None, barycentred=True,
                     spin_coherent=False, nbit=16, levels=None):
    """Generate a fake fold-mode PSRFITS archive with known injected
    parameters and write it to ``outfile``.  Returns the Archive.

    Signature parity with the reference (pplib.py:3302); start_MJD may
    be a utils.mjd.MJD or a float MJD; ``rng`` (numpy Generator or
    seed) makes the noise/scint draws reproducible.

    spin_coherent=True ties the absolute pulse phase of every subint to
    the spin ephemeris — each subint is additionally rotated by
    -frac(F0 (epoch - PEPOCH)), computed in exact rational arithmetic
    (the product is ~1e9 turns, beyond f64) — which is what
    polyco-driven folding (PSRCHIVE; reference write_archive installs
    polycos via set_ephemeris, pplib.py:3274-3281) produces on real
    archives.  With it, measured TOAs from different epochs phase-
    connect: a timing fit (timing.wideband_gls_fit) yields white
    residuals.  Default False preserves the simpler grid-aligned
    behavior (each archive's absolute phase arbitrary).

    **Binary pulsars** (ISSUE 11): when the ephemeris carries a
    complete ELL1 or BT element set (timing/binary.py semantics;
    partial sets and unsupported models raise loudly), spin_coherent
    folding additionally delays each subint by the orbital Roemer
    delay — the pulse phase becomes frac(F0 (epoch - Delta_R(epoch) -
    PEPOCH)) — so a campaign of these archives carries real orbital
    TOA modulation that timing.wideband_gls_fit models and fits.  The
    delay is evaluated at the SUBINT EPOCH; the measurement reports
    the TOA up to half a spin period away (the wrapped phase offset
    times P), where the true orbit has moved on — an injection-vs-
    model mistiming bounded by pi * A1 * P / PB seconds.  Keep the
    orbit mild enough that this sits below the TOA noise at test S/N
    (e.g. A1 = 0.05 lt-s, PB = 1 d, P = 4 ms leaves < 0.01 us).
    Binary keys without spin_coherent=True are
    ignored (grid-aligned archives carry no absolute phase at all).

    ``nbit``/``levels`` select the written DATA sample width and
    quantization depth (io/psrfits.write_archive_file): nbit=2 forges
    the sub-byte packed archives the raw streaming lane ships 32x
    smaller; levels=4 with nbit=8 forges the coarsely-quantized byte
    archives the transport-compression cost model packs on the fly.
    """
    rng = np.random.default_rng(rng)
    model = read_gmodel(modelfile, quiet=True) \
        if isinstance(modelfile, (str, bytes)) else modelfile
    par = parse_parfile(ephemeris) if isinstance(ephemeris, (str, bytes)) \
        else dict(ephemeris)
    PSR = par.get("PSR", par.get("PSRJ", "FAKE"))
    if "P0" in par:
        P0 = float(par["P0"])
    elif "F0" in par:
        P0 = 1.0 / float(par["F0"].replace("D", "E")
                         if isinstance(par["F0"], str) else par["F0"])
    else:
        raise ValueError("ephemeris needs P0 or F0")
    DM = float(par.get("DM", 0.0))
    PEPOCH = float(par.get("PEPOCH", 55000.0))

    chanwidth = bw / nchan
    lofreq = nu0 - bw / 2.0
    freqs = np.linspace(lofreq + chanwidth / 2.0,
                        lofreq + bw - chanwidth / 2.0, nchan)
    phases = (np.arange(nbin) + 0.5) / nbin

    noise_stds = np.broadcast_to(np.asarray(noise_stds, float),
                                 (nchan,)).copy()
    scales = np.broadcast_to(np.asarray(scales, float), (nchan,)).copy()
    if weights is None:
        weights = np.ones((nsub, nchan))
    weights = np.asarray(weights, float)

    if start_MJD is None:
        start_MJD = MJD.from_float(PEPOCH)
    elif not isinstance(start_MJD, MJD):
        start_MJD = MJD.from_float(float(start_MJD))
    epochs = [start_MJD.add_seconds((isub + 0.5) * tsub)
              for isub in range(nsub)]

    # clean dedispersed model portrait (tau from the modelfile is in
    # seconds and scatters during generation)
    base = np.asarray(gen_gmodel_portrait(model, phases, freqs, P=P0,
                                          quiet=True))
    # injected achromatic phase + dDM (or DM(nu) terms): delay the data
    delays = _dm_nu_delays(phase, dDM, P0, freqs, xs, Cs, nu_DM)
    rotmodel = rotate_phase(base, -delays)
    if t_scat and model.tau == 0.0:  # modelfile overrides
        from ..ops.scattering import scattering_portrait_FT, scattering_times

        taus = np.asarray(scattering_times(t_scat / P0, alpha, freqs, nu0))
        B = np.asarray(scattering_portrait_FT(taus, nbin // 2 + 1))
        rotmodel = np.fft.irfft(np.fft.rfft(rotmodel, axis=-1) * B,
                                n=nbin, axis=-1)

    spin_fracs = np.zeros(nsub)
    if spin_coherent:
        # frac(F0 * (epoch - PEPOCH)) per subint, exactly (~1e9 turns,
        # beyond f64) — shared rational helper so the timing fit
        # reduces with the identical F0 representation
        from ..timing.binary import binary_delay_np, parse_binary
        from ..utils.spin import rational, spin_F0, spin_phase_frac

        F0r = spin_F0(par)
        F0f = float(F0r)
        pep = rational(par.get("PEPOCH", PEPOCH))  # parsed once
        bp = parse_binary(par)  # None for isolated; loud on partial
        for isub, e in enumerate(epochs):
            frac = spin_phase_frac(F0r, pep, e.day, e.frac)
            if bp is not None:
                # the pulse is LATE by the orbital Roemer delay: phase
                # at the epoch is F0*(t - Delta_R - PEPOCH).  F0*Delta
                # is only ~1e2 turns, safe as a float product (and the
                # SAME float F0 the timing fit's remainder term uses)
                frac -= F0f * float(binary_delay_np(bp, e.day, e.frac))
            spin_fracs[isub] = frac % 1.0

    amps = np.zeros((nsub, npol, nchan, nbin))
    for isub in range(nsub):
        port = rotmodel
        if spin_coherent and spin_fracs[isub] != 0.0:
            # pulse earlier by the ephemeris phase at this epoch, so
            # epoch + phi*P phase-connects across the campaign
            port = rotate_phase(port, spin_fracs[isub])
        if scint is not False:
            if scint is True:
                port = add_scintillation(port, random=True, nsin=3,
                                         amax=1.0, wmax=5.0, rng=rng)
            else:
                port = add_scintillation(port, scint)
        for ipol in range(npol):
            # NB like the reference: pols are not realistic (same model
            # and noise level in every pol)
            noisy = scales[:, None] * port
            nz = noise_stds[:, None] * rng.standard_normal((nchan, nbin))
            amps[isub, ipol] = noisy + np.where(noise_stds[:, None] > 0,
                                                nz, 0.0)

    psrparam = [f"{k} {v}" for k, v in par.items()]
    arch = new_archive(
        amps, freqs, P0, epochs, tsub, weights=weights, DM=DM,
        dedispersed=True, source=PSR, telescope=telescope, nu0=nu0, bw=bw,
        state=("Intensity" if npol == 1 else state), psrparam=psrparam)
    if "RAJ" in par:
        arch.primary["RA"] = str(par["RAJ"])
    if "DECJ" in par:
        arch.primary["DEC"] = str(par["DECJ"])
    if barycentred:
        # the injected data carry no topocentric Doppler signature, so
        # mark the archive barycentred: Archive.doppler_factors() then
        # returns 1.0 instead of ephemeris-computed factors.  Pass
        # barycentred=False to test the Doppler-correction path.
        arch.primary["PPTBARY"] = True
    if not dedispersed:
        arch.dededisperse()
    arch.unload(outfile, nbit=nbit, levels=levels)
    if not quiet:
        print(f"\nUnloaded {outfile}.\n")
    return arch
