"""Tests for the LM engine and Gaussian template fitting.

Oracle (SURVEY.md §4): generate profiles/portraits from known Gaussian
parameters + noise, fit, assert recovery within uncertainties; bounds
respected; frozen parameters unchanged.
"""

import jax.numpy as jnp
import numpy as np

from pulseportraiture_tpu.fit.gauss import (fit_gaussian_portrait,
                                            fit_gaussian_profile,
                                            gen_gaussian_portrait_flat,
                                            gen_gaussian_profile_flat)
from pulseportraiture_tpu.fit.lm import levenberg_marquardt


def _rosenbrock_resid(x):
    return jnp.array([10.0 * (x[1] - x[0] ** 2.0), 1.0 - x[0]])


def _linear_resid(x, t, y, s):
    return (y - (x[0] + x[1] * t)) / s


class TestLM:
    def test_rosenbrock(self):
        res = levenberg_marquardt(_rosenbrock_resid, np.array([-1.2, 1.0]),
                                  max_iter=200)
        assert np.allclose(np.asarray(res.x), [1.0, 1.0], atol=1e-6)

    def test_linear_with_errors(self, rng):
        t = np.linspace(0, 1, 50)
        y = 2.0 + 3.0 * t + 0.1 * rng.normal(size=50)
        s = np.full(50, 0.1)
        res = levenberg_marquardt(_linear_resid, np.zeros(2), aux=(t, y, s))
        assert abs(float(res.x[0]) - 2.0) < 5 * float(res.x_err[0])
        assert abs(float(res.x[1]) - 3.0) < 5 * float(res.x_err[1])
        # analytic errors for weighted linear LS, scaled by red-chi2
        X = np.stack([np.ones(50), t]).T / 0.1
        cov = np.linalg.inv(X.T @ X)
        chi2 = float(res.chi2)
        scale = chi2 / 48.0
        assert np.allclose(np.asarray(res.x_err),
                           np.sqrt(np.diag(cov) * scale), rtol=0.05)

    def test_bounds_respected(self):
        # minimize (x-2)^2 with x <= 1 -> x -> 1
        res = levenberg_marquardt(lambda x: x - 2.0, np.array([0.0]),
                                  upper=np.array([1.0]), max_iter=100)
        assert float(res.x[0]) <= 1.0 + 1e-8
        assert float(res.x[0]) > 0.9

    def test_vary_mask_freezes(self):
        res = levenberg_marquardt(_rosenbrock_resid, np.array([-1.2, 1.0]),
                                  vary=np.array([False, True]), max_iter=100)
        assert float(res.x[0]) == -1.2
        assert float(res.x_err[0]) == 0.0


class TestGaussianProfile:
    def test_recover_two_gaussians(self, rng):
        nbin = 512
        truth = np.array([0.05, 0.0, 0.30, 0.04, 1.0, 0.55, 0.02, 0.6])
        prof = np.asarray(gen_gaussian_profile_flat(truth, nbin))
        noise = 0.01
        data = prof + noise * rng.normal(size=nbin)
        x0 = np.array([0.0, 0.0, 0.28, 0.05, 0.8, 0.57, 0.03, 0.5])
        res = fit_gaussian_profile(data, x0, noise)
        assert res.red_chi2 < 1.5
        # locations recovered well within a bin
        assert abs(res.fitted_params[2] - 0.30) < 2.0 / nbin
        assert abs(res.fitted_params[5] - 0.55) < 2.0 / nbin
        assert abs(res.fitted_params[4] - 1.0) < 0.05
        # tau frozen at 0 without fit_scattering
        assert res.fitted_params[1] == 0.0

    def test_recover_scattering(self, rng):
        nbin = 512
        truth = np.array([0.0, 12.0, 0.5, 0.03, 1.0])
        prof = np.asarray(gen_gaussian_profile_flat(truth, nbin))
        data = prof + 0.005 * rng.normal(size=nbin)
        x0 = np.array([0.0, 2.0, 0.49, 0.035, 0.9])
        res = fit_gaussian_profile(data, x0, 0.005, fit_scattering=True)
        assert abs(res.fitted_params[1] - 12.0) < 1.5

    def test_tau_seeded_at_bound_escapes(self, rng):
        # regression: a varying parameter starting exactly at its bound
        # must not be frozen by a zero transform derivative
        nbin = 512
        truth = np.array([0.0, 12.0, 0.5, 0.03, 1.0])
        prof = np.asarray(gen_gaussian_profile_flat(truth, nbin))
        data = prof + 0.005 * rng.normal(size=nbin)
        x0 = np.array([0.0, 0.0, 0.49, 0.035, 0.9])  # tau at bound 0
        res = fit_gaussian_profile(data, x0, 0.005, fit_scattering=True)
        assert res.fitted_params[1] > 5.0
        assert res.red_chi2 < 2.0


class TestGaussianPortrait:
    def test_recover_evolving_portrait(self, rng):
        nchan, nbin = 32, 256
        freqs = np.linspace(1300.0, 1700.0, nchan)
        nu_ref = 1500.0
        # dc, tau, loc, mloc, wid, mwid, amp, mamp (power-law code '000')
        truth = np.array([0.0, 0.0, 0.45, 0.02, 0.03, -0.3, 1.0, -1.5])
        port = np.asarray(gen_gaussian_portrait_flat(
            truth, freqs, nu_ref, nbin, alpha_s=-4.0))
        noise = 0.01
        data = port + noise * rng.normal(size=(nchan, nbin))
        x0 = np.array([0.0, 0.0, 0.44, 0.0, 0.035, 0.0, 0.9, 0.0])
        flags = np.array([1, 0, 1, 1, 1, 1, 1, 1])
        res = fit_gaussian_portrait(data, x0, -4.0, np.full(nchan, noise),
                                    flags, False, freqs, nu_ref)
        assert res.red_chi2 < 1.5
        p = res.fitted_params
        assert abs(p[2] - 0.45) < 2.0 / nbin     # loc
        assert abs(p[3] - 0.02) < 0.02           # loc evolution index
        assert abs(p[6] - 1.0) < 0.05            # amp
        assert abs(p[7] + 1.5) < 0.3             # spectral index

    def test_join_rotation_applied(self):
        nchan, nbin = 16, 128
        freqs = np.linspace(1300.0, 1700.0, nchan)
        theta = np.array([0.0, 0.0, 0.5, 0.0, 0.04, 0.0, 1.0, 0.0])
        base = np.asarray(gen_gaussian_portrait_flat(
            theta, freqs, 1500.0, nbin, alpha_s=-4.0))
        jm = np.zeros((1, nchan), bool)
        jm[0, :8] = True
        rot = np.asarray(gen_gaussian_portrait_flat(
            theta, freqs, 1500.0, nbin, alpha_s=-4.0,
            join_theta=np.array([[0.1, 0.0]]), join_mask=jm, P=0.003))
        # unjoined channels identical, joined channels rotated
        assert np.allclose(rot[8:], base[8:], atol=1e-12)
        assert not np.allclose(rot[:8], base[:8], atol=1e-3)
        shift = np.argmax(base[0]) - np.argmax(rot[0])
        assert abs((shift % nbin) - round(0.1 * nbin)) <= 1 or \
            abs((-shift % nbin) - round(0.1 * nbin)) <= 1
