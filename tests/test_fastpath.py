"""Fast-path validation: matmul real DFT parity with numpy's FFT, the
XLA harmonic-moment forms against each other, and end-to-end
fit_portrait_batch_fast parity with the complex-arithmetic
fit_portrait_batch.  (The Pallas moment kernel this file once covered
was deleted in round 4 — it measured slower than XLA's fused
reductions; see benchmarks/BENCHMARKS.md.)"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pulseportraiture_tpu.fit import fit_portrait_batch, fit_portrait_batch_fast
from pulseportraiture_tpu.fit.portrait import _moments_real_xla, _moments_xla
from pulseportraiture_tpu.ops.fourier import irfft_mm, rfft_mm
from pulseportraiture_tpu.synth import default_test_model, fake_portrait

P = 0.003
NCHAN, NBIN = 32, 512
FREQS = jnp.asarray(np.linspace(1200.0, 1999.0, NCHAN) + 0.5)


# --- matmul DFT ----------------------------------------------------------


@pytest.mark.parametrize("n", [64, 255, 1024])
def test_rfft_mm_matches_numpy(rng, n):
    x = jnp.asarray(rng.normal(size=(5, n)))
    Xr, Xi = rfft_mm(x)
    ref = np.fft.rfft(np.asarray(x))
    assert np.allclose(Xr, ref.real, atol=1e-10 * n)
    assert np.allclose(Xi, ref.imag, atol=1e-10 * n)


@pytest.mark.parametrize("n", [64, 255, 1024])
def test_irfft_mm_roundtrip(rng, n):
    x = jnp.asarray(rng.normal(size=(3, n)))
    Xr, Xi = rfft_mm(x)
    back = irfft_mm(Xr, Xi, n)
    assert np.allclose(back, x, atol=1e-11 * n)


# --- XLA moment forms ----------------------------------------------------


def test_moments_real_vs_complex(rng):
    """Split-real XLA moments == complex XLA moments (f64)."""
    nchan, nharm = 16, 129
    X = jnp.asarray(rng.normal(size=(nchan, nharm)) + 1j * rng.normal(size=(nchan, nharm)))
    t = jnp.asarray(rng.uniform(-0.5, 0.5, nchan))
    Cc, C1c, C2c = _moments_xla(t, X)
    Cr, C1r, C2r = _moments_real_xla(t, X.real, X.imag)
    assert np.allclose(Cc, Cr)
    assert np.allclose(C1c, C1r)
    assert np.allclose(C2c, C2r)


# --- end-to-end fast-path parity ----------------------------------------


def _batch(key, nb=4):
    model = default_test_model(nu_ref=1500.0)
    keys = jax.random.split(key, nb)
    phis = np.linspace(-0.2, 0.25, nb)
    dms = np.linspace(-2e-3, 3e-3, nb)
    ports, models, stds = [], [], []
    for k, phi, dm in zip(keys, phis, dms):
        pb = fake_portrait(k, model, FREQS, NBIN, P, phi=phi, DM=dm, noise_std=0.05)
        ports.append(pb.port)
        models.append(pb.model_port)
        stds.append(pb.noise_stds)
    return (jnp.stack(ports), jnp.stack(models), jnp.stack(stds)), phis, dms


def test_fast_batch_matches_reference(key):
    (ports, models, stds), phis, dms = _batch(key)
    a = fit_portrait_batch(ports, models, stds, FREQS, P, 1500.0)
    b = fit_portrait_batch_fast(ports, models, stds, FREQS, P, 1500.0)
    assert np.allclose(a.phi, b.phi, atol=1e-10)
    assert np.allclose(a.DM, b.DM, atol=1e-10)
    assert np.allclose(a.phi_err, b.phi_err, rtol=1e-6)
    assert np.allclose(a.DM_err, b.DM_err, rtol=1e-6)
    assert np.allclose(a.snr, b.snr, rtol=1e-8)
    assert np.allclose(a.chi2, b.chi2, rtol=1e-6)
    assert np.allclose(a.nu_DM, b.nu_DM, rtol=1e-8)
    # the fast path must still recover the injections
    assert np.abs(np.asarray(b.phi) - phis).max() < 1e-3


def test_fast_batch_shared_model(key):
    """A shared 2-D template gives the same answers as per-batch
    copies of it."""
    (ports, models, stds), phis, dms = _batch(key)
    shared = models[0]
    a = fit_portrait_batch_fast(
        ports, jnp.broadcast_to(shared, ports.shape), stds, FREQS, P,
        1500.0)
    b = fit_portrait_batch_fast(ports, shared, stds, FREQS, P, 1500.0)
    assert np.allclose(a.phi, b.phi, atol=1e-12)
    assert np.allclose(a.DM, b.DM, atol=1e-12)
    assert np.allclose(a.snr, b.snr, rtol=1e-10)


def test_fast_batch_masked_channels(key):
    (ports, models, stds), phis, dms = _batch(key)
    mask = jnp.ones(ports.shape[:2])
    mask = mask.at[:, ::5].set(0.0)
    a = fit_portrait_batch(
        ports, models, stds, FREQS, P, 1500.0, chan_masks=mask
    )
    b = fit_portrait_batch_fast(
        ports, models, stds, FREQS, P, 1500.0, chan_masks=mask
    )
    assert np.allclose(a.phi, b.phi, atol=1e-10)
    assert np.allclose(a.DM, b.DM, atol=1e-10)


@pytest.mark.slow
def test_fast_batch_routes_scattering_to_real_lane():
    """Since round 3 fit_portrait_batch_fast no longer rejects
    scattering work: tau/alpha flags and fixed nonzero tau seeds route
    to the complex-free _cgh_scatter lane (and an IR kernel with
    use_scatter=False explicitly forced off still raises)."""
    from pulseportraiture_tpu.fit import FitFlags

    args = (jnp.zeros((1, 4, 64)), jnp.zeros((1, 4, 64)),
            jnp.ones((1, 4)), jnp.linspace(1000.0, 1100.0, 4), P, 1050.0)
    r = fit_portrait_batch_fast(
        *args, fit_flags=FitFlags(True, True, False, True, False))
    assert r.phi.shape == (1,)
    theta0 = jnp.zeros((1, 5)).at[0, 3].set(1.0e-4)
    r2 = fit_portrait_batch_fast(*args, theta0=theta0)
    assert r2.phi.shape == (1,)
    with pytest.raises(ValueError, match="instrumental response"):
        fit_portrait_batch_fast(
            *args, use_scatter=False,
            ir_FT=np.ones((4, 33), complex))


class TestFusedCrossSpectrum:
    """ISSUE 14 tentpole (b): the hand-blocked fused DFT ->
    cross-spectrum program (ops/fused.py) — bitwise identity to the
    unfused stages, routing through prepare, and dead-knob
    normalization."""

    def _problem(self, nchan=24, nbin=256, seed=9):
        rng = np.random.default_rng(seed)
        port = jnp.asarray(rng.standard_normal((nchan, nbin)),
                           jnp.float32)
        model = jnp.asarray(rng.standard_normal((nchan, nbin)),
                            jnp.float32)
        w = jnp.asarray(rng.random((nchan, nbin // 2 + 1)) + 0.5,
                        jnp.float32)
        return port, model, w

    def test_block_size_invariance(self):
        """Channel blocking never changes a row's result: every block
        size — including non-divisor targets, where the channel axis
        is zero-padded up to a block multiple — produces
        bitwise-identical outputs.  (A 1-row block is excluded by
        design: it would lower to a gemv whose contraction order
        differs from the gemm rows — the reason ragged counts pad
        instead of degrading the block.)"""
        from pulseportraiture_tpu.ops.fused import fused_cross_spectrum

        port, model, w = self._problem()
        K = 64
        wk = w[:, :K]
        ref = None
        for block in (None, 24, 8, 7, 5):
            out = jax.jit(
                lambda p, m, w, b=block: fused_cross_spectrum(
                    p, m, w, K, fold=False, want_m2=True, block=b))(
                port, model, wk)
            out = tuple(np.asarray(o) for o in out)
            if ref is None:
                ref = out
                continue
            for x, y in zip(ref, out):
                assert np.array_equal(x, y), block

    def test_prepare_fused_vs_unfused_bitwise(self):
        """The real contract: prepare_portrait_fit_real and
        prepare_scatter_fit_real produce BITWISE-identical outputs
        fused vs unfused (both compiled — the only context the lanes
        ever run in; XLA's f32 FMA contraction makes an eager
        stage-by-stage reference a different program, not a valid
        oracle)."""
        from pulseportraiture_tpu.fit.portrait import (
            FitFlags, make_weights, prepare_portrait_fit_real,
            prepare_scatter_fit_real)

        port, model, _ = self._problem()
        K = 64
        nchan = port.shape[0]
        freqs = jnp.asarray(np.linspace(1300.0, 1900.0, nchan),
                            jnp.float32)
        w = make_weights(jnp.full(nchan, 0.1, jnp.float32),
                         port.shape[1])
        th0 = jnp.zeros(5, jnp.float32)

        def prep(fused):
            return jax.jit(
                lambda p, m, w, f, t: prepare_portrait_fit_real(
                    p, m, w, f, 0.003, 1500.0, t, nharm_eff=K,
                    fit_fused=fused))(port, model, w, freqs, th0)

        for x, y in zip(prep(False), prep(True)):
            assert np.array_equal(np.asarray(x), np.asarray(y))

        stds = jnp.full(nchan, 0.1, jnp.float32)
        cmask = jnp.ones(nchan, jnp.float32)
        th0s = jnp.asarray([0.0, 0.0, 0.0, -3.0, -4.0], jnp.float32)
        flags = FitFlags(True, True, False, True, False)

        def prep_sc(fused):
            return jax.jit(
                lambda p, m, s, c, f, t: prepare_scatter_fit_real(
                    p, m, s, c, f, 0.003, 1500.0, t, fit_flags=flags,
                    log10_tau=True, nharm_eff=K, fit_fused=fused))(
                port, model, stds, cmask, freqs, th0s)

        for x, y in zip(prep_sc(False), prep_sc(True)):
            assert np.array_equal(
                np.asarray(x.astype(jnp.float32)),
                np.asarray(y.astype(jnp.float32)))

    def test_bitwise_under_jit_and_vmap(self):
        from pulseportraiture_tpu.ops.fourier import rfft_mm
        from pulseportraiture_tpu.ops.fused import fused_cross_spectrum

        port, model, w = self._problem()
        K = 64
        wk = w[:, :K]
        ports = jnp.stack([port, port * 0.5 + 1.0])

        @jax.jit
        def unfused(p):
            dr, di = rfft_mm(p, nharm=K, fold=False)
            mr, mi = rfft_mm(model, nharm=K, fold=False)
            return ((dr * mr + di * mi) * wk,
                    (di * mr - dr * mi) * wk)

        @jax.jit
        def fused(p):
            Xr, Xi, _ = fused_cross_spectrum(p, model, wk, K,
                                             fold=False)
            return Xr, Xi

        a = jax.vmap(unfused)(ports)
        b = jax.vmap(fused)(ports)
        for x, y in zip(a, b):
            assert np.array_equal(np.asarray(x), np.asarray(y))

    def test_prepare_routes_through_fused(self, monkeypatch):
        """prepare_portrait_fit_real takes the fused path exactly when
        fit_fused resolves on AND a harmonic window is active (the
        dead-knob normalization)."""
        import pulseportraiture_tpu.ops.fused as fused_mod
        from pulseportraiture_tpu.fit.portrait import (
            make_weights, prepare_portrait_fit_real)

        port, model, _ = self._problem()
        freqs24 = jnp.asarray(
            np.linspace(1300.0, 1900.0, port.shape[0]), jnp.float32)
        w = make_weights(jnp.full(port.shape[0], 0.1, jnp.float32),
                         port.shape[1])
        th0 = jnp.zeros(5, jnp.float32)
        calls = []
        orig = fused_mod.fused_cross_spectrum

        def spy(*a, **k):
            calls.append(1)
            return orig(*a, **k)

        monkeypatch.setattr(fused_mod, "fused_cross_spectrum", spy)
        prepare_portrait_fit_real(port, model, w, freqs24, 0.003,
                                  1500.0, th0, nharm_eff=64,
                                  fit_fused=True)
        assert calls  # fused path taken
        calls.clear()
        # no window -> normalized onto the unfused program
        prepare_portrait_fit_real(port, model, w, freqs24, 0.003,
                                  1500.0, th0, nharm_eff=None,
                                  fit_fused=True)
        assert not calls
        # knob off -> unfused even with the window
        prepare_portrait_fit_real(port, model, w, freqs24, 0.003,
                                  1500.0, th0, nharm_eff=64,
                                  fit_fused=False)
        assert not calls

    def test_use_fit_fused_strict(self):
        from pulseportraiture_tpu.fit.portrait import use_fit_fused

        assert use_fit_fused(True) is True
        assert use_fit_fused(False) is False
        assert use_fit_fused("auto") in (True, False)
        with pytest.raises(ValueError, match="fit_fused"):
            use_fit_fused("sometimes")

    def test_pallas_kernel_available(self):
        """The Pallas kernels landed (ISSUE 16): availability is the
        module contract the streaming dispatch keys on, and the kernel
        runs under interpret mode on CPU.  Bitwise parity against the
        scan lives in tests/test_pallas_interpret.py."""
        from pulseportraiture_tpu.ops import fused

        assert fused.HAVE_PALLAS_FUSED is True
        port, model, w = self._problem(nchan=4, nbin=32)
        Xr, Xi, S0 = fused.fused_cross_spectrum_pallas(port, model,
                                                       w[:, :8], 8)
        assert Xr.shape == (4, 8) and Xi.shape == (4, 8)
        assert S0.shape == (4,)
