"""Per-backend autotune subsystem (ISSUE 19; ROADMAP item 5b).

Three layers:

- :mod:`~.capability` — the backend capability table + the single
  table-driven resolver every ``'auto'`` tri-state in config.py
  resolves through (no more scattered ``== "tpu"`` spellings).
- :mod:`~.store` — the persisted JSON tuning DB keyed (backend
  fingerprint, shape class): loud on corruption/staleness, atomic on
  write, zero re-sweeps on a warm hit.
- :mod:`~.autotune` — the reusable sweep harness (identity-preserving
  tier by default, numerics tier behind an explicit opt-in, min-of-N
  timing, per-candidate byte-identity gate, combined no-regression
  gate).
"""

from .autotune import (IDENTITY_TIER, NUMERICS_TIER, Knob, SweepResult,
                       apply_from_db, apply_knobs, ensure_tuned,
                       shape_class_for, sweep, tuned_config)
from .capability import (KNOB_POLARITY, CapabilityRecord,
                         backend_fingerprint, capability_record,
                         capability_summary, resolve_auto)
from .store import SCHEMA_VERSION, TuningStore

__all__ = [
    "KNOB_POLARITY", "CapabilityRecord", "backend_fingerprint",
    "capability_record", "capability_summary", "resolve_auto",
    "SCHEMA_VERSION", "TuningStore",
    "IDENTITY_TIER", "NUMERICS_TIER", "Knob", "SweepResult",
    "apply_from_db", "apply_knobs", "ensure_tuned", "shape_class_for",
    "sweep", "tuned_config",
]
