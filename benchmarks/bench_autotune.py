"""Per-backend autotune benchmark (ISSUE 19 acceptance gates).

Arms (all in ONE process):
  sweep     — a COLD tuning DB: the identity-tier sweep runs on the
              first archive (tune/autotune.ensure_tuned), winners
              persist to the DB keyed (backend fingerprint, shape
              class).  Gates: ``tuned_speedup`` >= 1.0 (the harness's
              own combined no-regression gate — a tuned campaign is
              never slower than default), and the FULL campaign's
              per-request ``.tim`` bytes under the tuned knobs are
              identical to the default config's (``tim_identical``) —
              the identity tier must never change output.
  reuse     — a WARM DB: ensure_tuned again on the same (fingerprint,
              shape class).  Gates: the workload fn is NEVER called
              (zero re-sweeps, counted), and the trace witnesses it as
              one ``tune_apply`` with ``db_hit=true`` and ZERO
              ``tune_sweep`` events (``db_reuse_ok``).
  fleet     — backend-aware routing (tentpole layer 3): a 2-host
              fast/slow fleet emulated with virtual devices — host1's
              fits pay a per-dispatch sleep, so its server-measured
              TOAs/s EMA (serve/server.py) genuinely drops and the
              ``stat`` wire op reports it.  The same request set runs
              with the router cost model OFF (exact least-loaded) and
              ON (cost = archives / measured relative speed).  Gates:
              cost-model makespan <= least-loaded makespan * 1.05
              (``cost_ok``), zero lost/duplicated requests, and every
              routed .tim byte-identical to its one-shot reference
              (``fleet_tim_identical``).

Knobs via env: PPT_NARCH (8), PPT_NSUB (4), PPT_NCHAN (16), PPT_NBIN
(128), PPT_NREQ (4 requests), PPT_TUNE_NRUN (2 timing reps),
PPT_SLOW_MS (150 per-dispatch penalty on the slow host),
PPT_CAMPAIGN_CACHE (corpus dir, shared with bench_campaign),
PPT_TELEMETRY (traces to <path>.tune1/.tune2/.fleet).  The tuning DB
is recreated under the corpus dir every run (the reuse arm needs a
same-process warm hit, not a stale file).  Prints ONE JSON line.
"""

import io
import json
import os
import sys
import time

sys.path.insert(0, __file__.rsplit("/", 2)[0])


def _ensure_devices(n):
    """Force >= n virtual CPU devices BEFORE jax initializes
    (bench_router's discipline): each emulated host pins its own
    device so its dispatches — and the slow host's penalty — run in
    its own worker."""
    if "jax" not in sys.modules:
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + f" --xla_force_host_platform_device_count={n}"
            ).strip()


def main():
    NHOSTS = 2
    _ensure_devices(NHOSTS)
    import pulseportraiture_tpu  # noqa: F401
    from pulseportraiture_tpu import config
    config.env_overrides()

    import jax

    from pulseportraiture_tpu import telemetry
    from pulseportraiture_tpu.io.gmodel import write_gmodel
    from pulseportraiture_tpu.pipeline.stream import stream_wideband_TOAs
    from pulseportraiture_tpu.serve import (InProcTransport, ToaClient,
                                            ToaRouter, ToaServer)
    from pulseportraiture_tpu.synth import default_test_model
    from pulseportraiture_tpu.synth.archive import make_fake_pulsar
    from pulseportraiture_tpu.tune import (TuningStore, ensure_tuned,
                                           shape_class_for, tuned_config)
    from pulseportraiture_tpu.tune.capability import backend_fingerprint

    NARCH = int(os.environ.get("PPT_NARCH", 8))
    NSUB = int(os.environ.get("PPT_NSUB", 4))
    NCHAN = int(os.environ.get("PPT_NCHAN", 16))
    NBIN = int(os.environ.get("PPT_NBIN", 128))
    NREQ = max(2, int(os.environ.get("PPT_NREQ", 4)))
    NRUN = max(1, int(os.environ.get("PPT_TUNE_NRUN", 2)))
    SLOW_MS = float(os.environ.get("PPT_SLOW_MS", 150.0))
    PAR = {"PSR": "FAKE", "P0": 0.003, "DM": 50.0, "PEPOCH": 56000.0}
    cache = os.environ.get("PPT_CAMPAIGN_CACHE", "/tmp/ppt_campaign")
    tag = f"{NARCH}x{NSUB}x{NCHAN}x{NBIN}"
    root = os.path.join(cache, tag)
    os.makedirs(root, exist_ok=True)
    trace_base = config.telemetry_path  # PPT_TELEMETRY (or None)

    mpath = os.path.join(root, "model.gmodel")
    if not os.path.exists(mpath):
        write_gmodel(default_test_model(1500.0), mpath, quiet=True)
    files = []
    for i in range(NARCH):
        path = os.path.join(root, f"a{i:04d}.fits")
        if not os.path.exists(path):
            make_fake_pulsar(mpath, PAR, outfile=path, nsub=NSUB,
                             nchan=NCHAN, nbin=NBIN, nu0=1500.0, bw=600.0,
                             phase=0.01 * (i % 50), dDM=1e-4 * (i % 40),
                             noise_stds=0.05, quiet=True, rng=i)
        files.append(path)
    slices = [files[i::NREQ] for i in range(NREQ)]

    out_root = os.path.join(root, "tune_out")
    os.makedirs(out_root, exist_ok=True)
    db = os.path.join(out_root, "tune_db.json")
    if os.path.exists(db):
        os.remove(db)  # the reuse arm witnesses THIS process's put

    # ---- sweep arm: cold DB ----------------------------------------
    shape_class = shape_class_for(NCHAN, NBIN)
    probe_tim = os.path.join(out_root, "probe.tim")
    n_workload_calls = [0]

    def run_fn(overrides):
        n_workload_calls[0] += 1
        with tuned_config(overrides):
            stream_wideband_TOAs(files[:1], mpath, tim_out=probe_tim,
                                 quiet=True)
        with open(probe_tim, "rb") as fh:
            return fh.read()

    run_fn({})  # warm the jit caches out of the swept window
    trace1 = f"{trace_base}.tune1" if trace_base else None
    tracer1, owned1 = telemetry.resolve_tracer(trace1, run="tune1")
    winners = ensure_tuned(run_fn, shape_class, db_path=db, nrun=NRUN,
                           tracer=tracer1, apply=False)
    if owned1:
        tracer1.close()
    ent = TuningStore(db).get(shape_class)
    assert ent is not None, "sweep arm persisted nothing"
    default_s, tuned_s = ent["default_s"], ent["tuned_s"]
    speedup = default_s / max(tuned_s, 1e-12)
    # the harness's combined no-regression gate guarantees this; a
    # violation means the gate itself broke
    speedup_ok = speedup >= 1.0
    assert speedup_ok, (default_s, tuned_s)

    # full-campaign byte gate: default refs vs tuned reruns
    def ref_tim(i):
        return os.path.join(out_root, f"ref{i}.tim")

    t0 = time.perf_counter()
    ntoa = 0
    for i, sl in enumerate(slices):
        res = stream_wideband_TOAs(sl, mpath, tim_out=ref_tim(i),
                                   quiet=True)
        ntoa += len(res.TOA_list)
    default_wall = time.perf_counter() - t0
    tims = [os.path.join(out_root, f"tuned{i}.tim") for i in range(NREQ)]
    t0 = time.perf_counter()
    with tuned_config(winners):
        for i, sl in enumerate(slices):
            stream_wideband_TOAs(sl, mpath, tim_out=tims[i], quiet=True)
    tuned_wall = time.perf_counter() - t0
    tim_identical = all(
        open(ref_tim(i), "rb").read() == open(tims[i], "rb").read()
        for i in range(NREQ))
    assert tim_identical, (
        "identity-tier winners changed campaign .tim bytes: "
        f"{winners}")

    # ---- reuse arm: warm DB, zero re-sweeps ------------------------
    trace2 = f"{trace_base}.tune2" if trace_base else None
    tracer2, owned2 = telemetry.resolve_tracer(trace2, run="tune2")
    calls_before = n_workload_calls[0]
    winners2 = ensure_tuned(run_fn, shape_class, db_path=db, nrun=NRUN,
                            tracer=tracer2, apply=False)
    if owned2:
        tracer2.close()
    resweeps = n_workload_calls[0] - calls_before
    db_reuse_ok = resweeps == 0 and winners2 == winners
    assert db_reuse_ok, (
        f"warm DB re-swept: {resweeps} workload calls, "
        f"{winners2} != {winners}")
    if trace2:
        man, evs = telemetry.load_trace(trace2)
        applies = [e for e in evs if e["type"] == "tune_apply"]
        sweeps = [e for e in evs if e["type"] == "tune_sweep"]
        assert applies and applies[0]["db_hit"] is True, applies
        assert not sweeps, "warm DB still emitted tune_sweep events"
        telemetry.validate_trace(trace2)

    # ---- fleet arm: fast/slow 2-host cost-model placement ----------
    ndev = len(jax.local_devices())
    if ndev < NHOSTS:
        raise SystemExit(
            f"bench_autotune: {NHOSTS} emulated hosts need {NHOSTS} "
            f"virtual devices, got {ndev} (jax initialized before the "
            "device-count flag could apply?)")
    from pulseportraiture_tpu.pipeline import stream as S

    slow_dev = jax.local_devices()[1]
    real_fit_fn = S._raw_fit_fn

    def hobbled_fit_fn(*a, **kw):
        fn = real_fit_fn(*a, **kw)

        def run(*args):
            out = jax.block_until_ready(fn(*args))
            leaf = jax.tree_util.tree_leaves(out)[0]
            try:
                on_slow = slow_dev in leaf.devices()
            except Exception:
                on_slow = False
            if on_slow:
                time.sleep(SLOW_MS / 1e3)
            return out

        return run

    S._raw_fit_fn = hobbled_fit_fn
    fleet = None
    try:
        servers = [
            ToaServer(quiet=True,
                      stream_devices=[jax.local_devices()[h]]).start()
            for h in range(NHOSTS)]
        # warm EVERY host's jit caches AND its measured-TOAs/s EMA —
        # the slow host's per-dispatch penalty lands in its rate, so
        # the stat op reports genuinely different speeds
        for srv in servers:
            for _ in range(2):
                ToaClient(srv).get_TOAs(files[:1], mpath, timeout=600)
        rates = [srv.stats()["toas_per_s"] for srv in servers]
        assert all(r is not None and r > 0 for r in rates), rates
        walls = {}
        shares = {}
        fleet_tim_ok = True
        lost = 0
        for cm in (False, True):
            label = "cost" if cm else "ll"
            trace = f"{trace_base}.fleet.{label}" if trace_base else None
            router = ToaRouter(
                [InProcTransport(srv, label=f"{label}{h}")
                 for h, srv in enumerate(servers)],
                telemetry=trace, cost_model=cm)
            arm_tims = [os.path.join(out_root, f"{label}_r{i}.tim")
                        for i in range(NREQ)]
            t0 = time.perf_counter()
            handles = [router.submit(sl, mpath, tim_out=arm_tims[i],
                                     name=f"req{i}")
                       for i, sl in enumerate(slices)]
            results = [h.result(3600) for h in handles]
            walls[label] = time.perf_counter() - t0
            shares[label] = {lbl: st["n_archives"]
                             for lbl, st in router.stats().items()}
            router.close()
            lost += NREQ - len(results)
            arm_ntoa = sum(len(r.TOA_list) for r in results)
            assert arm_ntoa == ntoa, (
                f"{label} arm produced {arm_ntoa} TOAs, one-shot "
                f"{ntoa} — lost or duplicated work")
            for i in range(NREQ):
                fleet_tim_ok = fleet_tim_ok and (
                    open(ref_tim(i), "rb").read()
                    == open(arm_tims[i], "rb").read())
            if trace:
                summary = telemetry.report(trace, file=io.StringIO())
                assert summary["n_route_done"] == NREQ, summary
        # the gate: backend-aware placement must never lose to blind
        # least-loaded (1.05 tolerance for scheduling noise at tiny
        # shapes)
        cost_ok = walls["cost"] <= walls["ll"] * 1.05
        assert cost_ok, (
            f"cost-model makespan {walls['cost']:.3f}s > least-loaded "
            f"{walls['ll']:.3f}s * 1.05")
        assert lost == 0 and fleet_tim_ok, (lost, fleet_tim_ok)
        fleet = {
            "slow_ms": SLOW_MS,
            "toas_per_s": [round(r, 2) for r in rates],
            "makespan_ll_s": round(walls["ll"], 3),
            "makespan_cost_s": round(walls["cost"], 3),
            "placement_ll": shares["ll"],
            "placement_cost": shares["cost"],
            "cost_ok": bool(cost_ok),
            "lost_requests": lost,
            "fleet_tim_identical": bool(fleet_tim_ok),
        }
    finally:
        S._raw_fit_fn = real_fit_fn
        for srv in servers:
            srv.stop()

    print(json.dumps({
        "metric": f"identity-tier autotune sweep + campaign, {NARCH} "
                  f"archives x {NSUB}sub x {NCHAN}ch x {NBIN}bin, "
                  f"shape class {shape_class}",
        "value": round(speedup, 4),
        "unit": "x tuned speedup (workload min-of-N, >= 1.0 by the "
                "no-regression gate)",
        "fingerprint": backend_fingerprint(),
        "winners": {k: repr(v) for k, v in winners.items()},
        "n_swept": ent["n_swept"],
        "default_s": round(default_s, 4),
        "tuned_s": round(tuned_s, 4),
        "speedup_ok": bool(speedup_ok),
        "campaign_default_wall_s": round(default_wall, 3),
        "campaign_tuned_wall_s": round(tuned_wall, 3),
        "tim_identical": bool(tim_identical),
        "db_reuse_ok": bool(db_reuse_ok),
        "resweeps_on_warm_db": resweeps,
        "fleet": fleet,
        "toas": ntoa,
        "device": str(jax.devices()[0]),
    }))


if __name__ == "__main__":
    main()
