"""Device-resident campaign ceiling (link-free config 5).

BENCHMARKS.md round 2 claimed "a real host would stream thousands of
TOAs/s" because the tunneled link eats ~90% of campaign wall — but the
number was extrapolated.  This bench RECORDS it: the streaming driver's
fused raw-bucket program (pipeline/stream._raw_fit_fn — int16 decode,
min-window baseline, power-spectrum noise, S/N, nu_fit seeding, batched
fit, result packing) runs on DEVICE-RESIDENT data, K dispatches
back-to-back with one scalar pull, slope-timed.  That is the per-chip
compute ceiling a locally-attached host sees once IO keeps up
(prefetch threads + the raw int16 lane at ~2x effective link bytes).

Knobs via env: PPT_NSUBB (bucket size, default 256), PPT_NCHAN (256),
PPT_NBIN (1024).  Prints ONE JSON line like bench.py.
"""

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, __file__.rsplit("/", 2)[0])


def main():
    import jax
    import jax.numpy as jnp

    import pulseportraiture_tpu  # noqa: F401
    from pulseportraiture_tpu import config
    config.dft_precision = "default"
    config.cross_spectrum_dtype = "bfloat16"

    from benchmarks.common import bench_model, devtime
    from pulseportraiture_tpu.pipeline.stream import _raw_fit_fn

    NSUBB = int(os.environ.get("PPT_NSUBB", 256))
    NCHAN = int(os.environ.get("PPT_NCHAN", 256))
    NBIN = int(os.environ.get("PPT_NBIN", 1024))
    P, NU0 = 0.003, 1500.0
    DT = jnp.float32

    model, freqs = bench_model(NCHAN, NBIN)

    # raw int16 bucket, host-built once, device-resident thereafter
    rng = np.random.default_rng(0)
    clean = np.asarray(model, np.float32)
    ports = clean[None] * (1.0 + 0.1 * rng.standard_normal(
        (NSUBB, 1, 1)).astype(np.float32))
    ports = ports + 0.05 * rng.standard_normal(ports.shape).astype(
        np.float32)
    lo, hi = ports.min(axis=-1), ports.max(axis=-1)
    scl = np.maximum((hi - lo) / 65000.0, 1e-12).astype(np.float32)
    offs = ((hi + lo) / 2.0).astype(np.float32)
    raw = np.clip(np.round((ports - offs[..., None]) / scl[..., None]),
                  -32767, 32767).astype(np.int16)

    flags = (True, True, False, False, False)
    from pulseportraiture_tpu.fit.portrait import resolve_harmonic_window

    hwin = resolve_harmonic_window(None, clean, NBIN)
    fn = _raw_fit_fn(NCHAN, NBIN, flags, 25, False, "none", True,
                     "float32", x_bf16=True, nharm_eff=hwin)
    d = {
        "raw": jnp.asarray(raw), "scl": jnp.asarray(scl, DT),
        "offs": jnp.asarray(offs, DT),
        "cmask": jnp.ones((NSUBB, NCHAN), DT),
        "model": jnp.asarray(clean, DT), "freqs": jnp.asarray(freqs, DT),
        "Ps": jnp.full((NSUBB,), P, DT),
        "DMg": jnp.zeros((NSUBB,), DT),
        "turns": jnp.zeros((NSUBB, 1), DT),
    }
    jax.block_until_ready(d["raw"])

    def run():
        return fn(d["raw"], d["scl"], d["offs"], d["cmask"], d["model"],
                  d["freqs"], d["Ps"], d["DMg"], DT(-1.0), DT(0.0),
                  DT(1.0), DT(0.0), DT(0.0), d["turns"], None, None)

    r = run()
    phi = np.asarray(r)[0]
    assert np.all(np.isfinite(phi)), "non-finite phases"
    slope, single = devtime(run, lambda rr: rr)
    print(json.dumps({
        "metric": f"device-resident raw campaign buckets, {NSUBB}sub x "
                  f"{NCHAN}ch x {NBIN}bin (decode+stats+fit+pack)",
        "value": round(NSUBB / slope, 1),
        "unit": "TOAs/sec",
        "bucket_latency_ms": round(single * 1e3, 1),
        "device": str(jax.devices()[0]),
    }))


if __name__ == "__main__":
    main()
