"""Wideband timing: .tim reading, a NumPy GLS fitter with ELL1/BT
binary-orbit models, and the fleet-batched solve lane.

Closes the loop the reference's example notebook closes with an
external ``tempo`` GLS run on the produced .tim with DMDATA 1
(examples/example_make_model_and_TOAs.ipynb cells 43-56) — here with
no external binaries: read the wideband TOAs (+ -pp_dm DM
measurements) back, fit a linearized timing model jointly to arrival
times and DMs, and report white(ned) residuals.  Binary pulsars
(ISSUE 11) fit their Keplerian ELL1/BT elements alongside spin/DMX;
timing/fleet.py batches the per-pulsar solves into padded device
dispatches (the ``pptime`` CLI and stream_ipta_campaign's
timing_pars= ride it).
"""

from .binary import BinaryParams, parse_binary
from .fleet import TimingJob, fleet_gls_fit, toas_from_measurements
from .gls import WidebandGLSResult, wideband_gls_fit
from .incremental import GLSDriftError, IncrementalGLS
from .tim import TimTOA, read_tim

__all__ = ["read_tim", "TimTOA", "wideband_gls_fit",
           "WidebandGLSResult", "BinaryParams", "parse_binary",
           "TimingJob", "fleet_gls_fit", "toas_from_measurements",
           "IncrementalGLS", "GLSDriftError"]
