"""Wideband generalized-least-squares timing fit (NumPy, float64).

The DMDATA-1 likelihood the reference validates with an external tempo
run (examples/example_make_model_and_TOAs.ipynb cells 43-56): arrival
times AND the per-TOA wideband DM measurements enter one weighted
least-squares system,

    chi^2 = sum_i ((t_res_i - A_t_i @ x) / sigma_t_i)^2
          + sum_i ((DM_i - DM_model(t_i) - A_d_i @ x) / sigma_DM_i)^2

linearized about a barycentric spin ephemeris (F0 [, F1] at PEPOCH)
plus a piecewise-constant DM model (DMX per observing epoch — exactly
the structure make_fake_pulsar injects) plus, since ISSUE 11, an
orbital Roemer delay for ELL1/BT binaries (timing/binary.py) with its
Keplerian elements in the fit.  White noise only; Shapiro and
relativistic orbital terms remain unmodeled and are refused loudly.

The LINEARIZATION (exact rational spin-phase reduction, binary delay
evaluation, design-column assembly) is host work — timing needs
~1e-13 day precision, beyond f32 and beneath any dispatch floor at a
handful of TOAs.  The SOLVE is factored out (``build_gls_system`` /
``gls_solve_np``) so timing/fleet.py can batch it: one padded device
dispatch solves the whole pulsar fleet's systems with this module's
single-pulsar path as the digit oracle.  Both lanes run the same
algorithm — column-normalized normal equations through a
pseudoinverse — so serial-vs-batched stays digit-comparable.
"""

from dataclasses import dataclass

import numpy as np

from ..config import Dconst
from . import binary as _binary

__all__ = ["wideband_gls_fit", "WidebandGLSResult", "build_gls_system",
           "gls_solve_np", "finalize_gls"]

SECPERDAY = 86400.0

# Binary-orbit parfile keys, split by modeling status (ISSUE 11
# demotes the old blanket refusal):
#
# * _SUPPORTED_BINARY_KEYS enter the timing model (timing/binary.py):
#   Keplerian ELL1/BT elements plus their secular DOT derivatives.
# * _UNMODELED_BINARY_KEYS still refuse loudly: Shapiro delay in both
#   its (M2, SINI) and orthometric (H3, H4, STIG) parameterizations —
#   the orthometric keys used to slip PAST the old refusal and get
#   silently mistimed — plus relativistic/alternate-parameterization
#   terms (GAMMA, OMDOT, FB-series, geometry keys).  Silently ignoring
#   any of them would produce arrival-time residuals with unmodeled
#   orbital structure that the fitted columns partially absorb — a
#   misfit with no visible symptom.
_SUPPORTED_BINARY_KEYS = frozenset({
    "BINARY", "PB", "A1",
    "TASC", "EPS1", "EPS2",              # ELL1 elements
    "T0", "ECC", "E", "OM",              # BT elements
    "PBDOT", "XDOT", "A1DOT",            # secular derivatives
    "EPS1DOT", "EPS2DOT",
})
_UNMODELED_BINARY_KEYS = frozenset({
    # Shapiro delay (classic and orthometric parameterizations)
    "SINI", "M2", "SHAPMAX", "H3", "H4", "STIG",
    # relativistic / alternate-parameterization terms
    "GAMMA", "OMDOT", "ECCDOT", "EDOT", "FB0", "FB1",
    "MTOT", "KOM", "KIN",
})
# Back-compat: the union is what the pre-ISSUE-11 blanket refusal
# covered (callers/tests grep this name).
_BINARY_KEYS = _SUPPORTED_BINARY_KEYS | _UNMODELED_BINARY_KEYS


@dataclass
class WidebandGLSResult:
    params: dict              # name -> fitted offset value
    param_errs: dict
    time_resids_us: np.ndarray   # post-fit [us]
    prefit_resids_us: np.ndarray
    dm_resids: np.ndarray        # post-fit DM residuals [pc cm^-3]
    toa_errs_us: np.ndarray
    dm_errs: np.ndarray
    epochs: np.ndarray           # epoch index per TOA
    dmx: np.ndarray              # fitted DMX per epoch [pc cm^-3]
    dmx_errs: np.ndarray
    chi2: float
    dof: int
    wrms_us: float
    n_dropped_no_dm: int = 0     # input TOAs without -pp_dm/-pp_dme
    binary: object = None        # timing.binary.BinaryParams or None

    @property
    def red_chi2(self):
        return self.chi2 / max(self.dof, 1)


def _group_epochs(mjds, gap_days=0.5):
    """Epoch index per TOA: a new epoch wherever the (sorted) MJDs jump
    by more than gap_days."""
    order = np.argsort(mjds)
    out = np.zeros(len(mjds), int)
    cur = 0
    prev = None
    for j in order:
        if prev is not None and mjds[j] - prev > gap_days:
            cur += 1
        out[j] = cur
        prev = mjds[j]
    return out


def build_gls_system(toas, par, fit_f0=True, fit_f1=False,
                     fit_binary=True, epoch_gap_days=0.5,
                     allow_wraps=False):
    """Linearize the wideband timing model about ``par`` — everything
    except the solve.

    Returns a dict-like system (plain attributes via a small class
    would be overkill; the fleet lane treats it as data):
      A          (2n, p) whitened stacked design matrix
      r          (2n,)  whitened stacked residual vector
      names      fitted global-parameter names (pre-DMX columns)
      nep        number of DMX epochs
      epochs, sig_t, dm_errs, errs_us, r_t, r_d, n, n_dropped, binary
    Raises exactly like the old monolithic fit: missing PEPOCH/F0,
    unmodeled binary keys, partial binary element sets, < 2 usable
    TOAs, lost phase connection.
    """
    def fget(key, default=None):
        v = par.get(key, default)
        return float(str(v).replace("D", "E")) if v is not None else None

    # refuse parfiles whose binary keys this model does NOT implement,
    # LOUDLY: fitting anyway would silently time the pulsar against a
    # wrong (orbit-smeared) phase prediction.  Keplerian ELL1/BT
    # elements are modeled (timing/binary.py); Shapiro/relativistic
    # terms are not.
    unmodeled = sorted(k for k in _UNMODELED_BINARY_KEYS
                       if par.get(k) is not None) if hasattr(par, "get") \
        else []
    if unmodeled:
        raise ValueError(
            "wideband_gls_fit: the parfile carries binary-orbit "
            f"parameters ({', '.join(unmodeled)}) that this fit does "
            "not model — it implements Keplerian ELL1/BT orbits "
            "(PB, A1, TASC/T0, EPS1/EPS2 or ECC/OM, and their DOT "
            "derivatives) but no Shapiro or relativistic terms.  "
            "Remove them, or time these TOAs with tempo2/PINT.")
    bp = _binary.parse_binary(par) if hasattr(par, "get") else None

    PEPOCH = fget("PEPOCH")
    if PEPOCH is None:
        raise ValueError(
            "wideband_gls_fit: parfile is missing PEPOCH (the spin "
            "reference epoch); add a 'PEPOCH <mjd>' line")
    if fget("F0") is None and fget("P0") is None:
        raise ValueError(
            "wideband_gls_fit: parfile has neither F0 nor P0; one spin "
            "parameter is required")
    DM0 = fget("DM", 0.0)

    n_in = len(toas)
    toas = [t for t in toas if t.dm is not None and t.dm_err]
    n = len(toas)
    n_dropped = n_in - n
    if n_dropped:
        import warnings

        warnings.warn(
            f"wideband_gls_fit: dropped {n_dropped} of {n_in} TOAs "
            "without -pp_dm/-pp_dme wideband DM flags (they cannot "
            "enter the DMDATA system)", stacklevel=2)
    if n < 2:
        raise ValueError("wideband GLS needs >= 2 TOAs with -pp_dm")
    freqs = np.array([t.frequency for t in toas])
    errs_us = np.array([t.error_us for t in toas])
    dms = np.array([t.dm for t in toas])
    dm_errs = np.array([t.dm_err for t in toas])
    mjd_i = np.array([t.mjd_int for t in toas], np.int64)
    mjd_f = np.array([t.mjd_frac for t in toas])
    mjds = mjd_i + mjd_f

    epochs = _group_epochs(mjds, epoch_gap_days)
    nep = epochs.max() + 1

    # orbital Roemer delay of the par's binary model at each TOA, plus
    # the closed-form partials for the design columns.  Evaluated at
    # the (topocentric=barycentric here) arrival epoch; the ~ms
    # dispersion offset changes the orbital phase by ~2pi*ms/PB —
    # orders below the TOA errors.  The jittable op is the production
    # lane (the same partials feed the fleet's batched systems); the
    # NumPy oracle in timing/binary.py guards its digits.
    delay_s = 0.0
    dparts = None
    if bp is not None:
        d, parts = _binary.binary_delay_and_partials(bp, mjd_i, mjd_f)
        delay_s = np.asarray(d, np.float64)
        dparts = np.asarray(parts, np.float64)

    # infinite-frequency arrival time: subtract the MODEL dispersion
    # delay (par DM; the DMX corrections are fitted linearly below) at
    # the TOA's reference frequency.  Using the measured DMs here would
    # leak their noise into the arrival times and double-count the DMX
    # columns.
    disp_s = np.where(np.isfinite(freqs),
                      Dconst * DM0 * freqs ** -2.0, 0.0)
    # seconds since PEPOCH (f64: used only for design columns, where
    # ns precision is irrelevant)
    dt_s = ((mjd_i - int(PEPOCH)) * SECPERDAY
            + (mjd_f - (PEPOCH - int(PEPOCH))) * SECPERDAY
            - disp_s - delay_s)

    # prefit phase residuals (nearest-turn wrap).  F0 * dt is ~1e9
    # turns for an MSP campaign — one f64 product would cost ns-level
    # rounding — so the integer-day part is reduced modulo 1 in exact
    # rational arithmetic via the SAME helper/representation the
    # spin-coherent synth uses (utils/spin.py; a float-rounded F0 here
    # would fake a ~1 ns/100 days residual slope against it), and only
    # the < half-day remainder (~1e7 turns, ~0.01 ns f64 error) is a
    # float product.  The binary delay is seconds-scale, so its phase
    # F0*delay (~1e2 turns) is safe as a float product.
    from ..utils.spin import day_phase_frac, spin_F0

    F0r = spin_F0(par)
    F0 = float(F0r)  # design/conversion value, consistent with F0r
    pep_i = int(PEPOCH)
    phase_day = np.array(
        [day_phase_frac(F0r, pep_i, di) for di in mjd_i])
    phase_rem = F0 * ((mjd_f - (PEPOCH - pep_i)) * SECPERDAY
                      - disp_s - delay_s)
    phase = phase_day + phase_rem
    dphase = phase - np.round(phase)
    # phase-connection validation.  Nearest-turn wrapping is only valid
    # when every TRUE residual phase sits inside a +-0.5-turn window
    # around a common offset (the OFFSET parameter absorbs the mean).
    # The observable, rotation-invariant symptom of lost connection is
    # the OCCUPIED CIRCULAR ARC of the prefit residuals: residuals of
    # a connected campaign cluster (any cluster position is fine —
    # a constant offset at the +-0.5 boundary must NOT false-fire),
    # while a drifting-F0 campaign smears them over the circle.  When
    # more than half the circle is occupied no single wrap window can
    # contain the data and the fit would silently time wrapped
    # aliases.  A badly-wrong binary model trips this too — by design:
    # its orbit-smeared prediction IS lost phase connection.
    if not allow_wraps and n > 1:
        s = np.sort(dphase)
        largest_gap = max(float(np.diff(s).max(initial=0.0)),
                          1.0 - float(s[-1] - s[0]))
        occupied = 1.0 - largest_gap
        if occupied > 0.5:
            raise ValueError(
                "wideband_gls_fit: prefit phase residuals occupy "
                f"{occupied:.2f} turns of the phase circle — phase "
                "connection is lost and the nearest-turn wrap would "
                "silently time wrapped aliases.  Improve F0/F1"
                + ("/the binary model" if bp is not None else "")
                + " (or pass allow_wraps=True to accept per-TOA "
                "wrapping).")
    r_t = dphase / F0  # seconds

    # design matrix, time rows: d(model delay)/d(param) in seconds
    cols = {}
    cols["OFFSET"] = np.ones(n)
    # spin columns carry tempo's sign convention: the fitted value is
    # the CORRECTION TO ADD to the par parameter (residuals shrink when
    # the par moves toward truth)
    if fit_f0:
        cols["F0"] = -dt_s / F0
    if fit_f1:
        cols["F1"] = -0.5 * dt_s ** 2.0 / F0
    # binary columns: d(Roemer delay)/d(element) — a pulse is LATE by
    # the extra delay, so the column is +d(delay)/d(param) and the
    # fitted value is again the correction to ADD to the par element
    if bp is not None and fit_binary:
        for name, row in zip(bp.param_names, dparts):
            cols[name] = row
    # DMX columns affect BOTH the time rows (through the dispersion
    # delay at the TOA frequency) and the DM rows
    names = list(cols)
    A_t = np.stack([cols[k] for k in names], axis=1)
    dmx_t = np.zeros((n, nep))
    finite = np.isfinite(freqs)
    for j in range(nep):
        sel = (epochs == j) & finite
        dmx_t[sel, j] = Dconst * freqs[sel] ** -2.0
    A_t = np.concatenate([A_t, dmx_t], axis=1)

    # DM rows: residual = DM_i - (DM0 + DMX[epoch])
    r_d = dms - DM0
    A_d = np.zeros((n, A_t.shape[1]))
    for j in range(nep):
        A_d[epochs == j, len(names) + j] = 1.0

    # stack and whiten
    sig_t = errs_us * 1e-6
    A = np.concatenate([A_t / sig_t[:, None], A_d / dm_errs[:, None]])
    r = np.concatenate([r_t / sig_t, r_d / dm_errs])

    from ..utils.bunch import DataBunch

    return DataBunch(A=A, r=r, names=names, nep=nep, epochs=epochs,
                     sig_t=sig_t, dm_errs=dm_errs, errs_us=errs_us,
                     r_t=r_t, r_d=r_d, n=n, n_dropped=n_dropped,
                     binary=bp)


def gls_solve_np(A, r):
    """Host-NumPy solve of one whitened system — the per-pulsar oracle
    the fleet's batched device program mirrors op-for-op.

    Column-normalize (the raw design spans ~12 decades: seconds-per-Hz
    vs seconds-per-DM columns, which wrecks both conditioning and the
    pseudoinverse's singular-value threshold), solve the normal
    equations through a pseudoinverse (rank-deficient columns — e.g.
    an all-zero pad column in the fleet lane — drop out with zero
    value and zero error instead of blowing up), and return
    (x, perr, cov, post, chi2) with ``post`` the whitened post-fit
    residual vector."""
    col = np.sqrt((A ** 2.0).sum(axis=0))
    col = np.where(col > 0, col, 1.0)
    An = A / col
    N = np.linalg.pinv(An.T @ An)
    xn = N @ (An.T @ r)
    x = xn / col
    cov = (N / col[:, None]) / col[None, :]
    perr = np.sqrt(np.maximum(np.diag(cov), 0.0))
    post = r - An @ xn
    chi2 = float((post ** 2.0).sum())
    return x, perr, cov, post, chi2


def finalize_gls(system, x, perr, post, chi2):
    """Assemble a WidebandGLSResult from a solved system (shared by
    the single-pulsar path and the fleet lane)."""
    s = system
    n = s.n
    nglob = len(s.names)
    post_t = post[:n] * s.sig_t
    post_d = post[n:2 * n] * s.dm_errs
    dof = 2 * n - (nglob + s.nep)
    w = s.sig_t ** -2.0
    wrms = np.sqrt((post_t ** 2.0 * w).sum() / w.sum()) * 1e6
    params = dict(zip(s.names, x[:nglob]))
    param_errs = dict(zip(s.names, perr[:nglob]))
    return WidebandGLSResult(
        params=params, param_errs=param_errs,
        time_resids_us=post_t * 1e6, prefit_resids_us=s.r_t * 1e6,
        dm_resids=post_d, toa_errs_us=s.errs_us, dm_errs=s.dm_errs,
        epochs=s.epochs, dmx=x[nglob:nglob + s.nep],
        dmx_errs=perr[nglob:nglob + s.nep],
        chi2=chi2, dof=dof, wrms_us=float(wrms),
        n_dropped_no_dm=s.n_dropped, binary=s.binary)


def wideband_gls_fit(toas, par, fit_f0=True, fit_f1=False,
                     fit_binary=True, epoch_gap_days=0.5,
                     allow_wraps=False):
    """Fit (phase offset[, dF0[, dF1]][, binary elements], DMX per
    epoch) to wideband TOAs.

    toas: list of timing.tim.TimTOA (needs frequency, mjd, error_us,
    dm, dm_err).  par: dict-like with F0 or P0, PEPOCH, DM (the
    parse_parfile output is fine — string values are converted).  A
    parfile with a complete ELL1 (PB/A1/TASC[/EPS1/EPS2]) or BT
    (PB/A1/T0[/ECC/OM]) element set gets its orbital Roemer delay
    modeled and — with fit_binary=True — its Keplerian elements
    fitted as corrections (dPB, dA1, dTASC/dT0, dEPS1/dECC,
    dEPS2/dOM) alongside the spin/DMX columns.  Shapiro and
    relativistic keys (SINI/M2/H3/H4/STIG/GAMMA/OMDOT/...) are still
    refused loudly, as are partial or unsupported binary models.

    Returns WidebandGLSResult; DM measurements and arrival times are
    fit jointly (DMDATA-1 style), with the model DM at each TOA =
    par DM + DMX[epoch].

    TOAs lacking wideband DM measurements cannot enter the DMDATA
    system; they are dropped with a warning and counted in the
    result's n_dropped_no_dm (they used to vanish silently).

    Phase connection is validated: each prefit residual is wrapped to
    the nearest turn independently, which is only meaningful when the
    ephemeris predicts phase to well under half a turn across the
    campaign.  If the wrapped residuals occupy more than half the
    phase circle, the pulse numbering is ambiguous and the fit would
    silently time a wrapped alias — that raises unless
    allow_wraps=True (for callers who accept per-TOA wrapping, e.g.
    offset-only fits on scrambled data)."""
    system = build_gls_system(toas, par, fit_f0=fit_f0, fit_f1=fit_f1,
                              fit_binary=fit_binary,
                              epoch_gap_days=epoch_gap_days,
                              allow_wraps=allow_wraps)
    x, perr, _, post, chi2 = gls_solve_np(system.A, system.r)
    return finalize_gls(system, x, perr, post, chi2)
