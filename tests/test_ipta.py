"""Multi-pulsar IPTA campaign driver (BASELINE config 5 orchestration):
per-pulsar models/buckets/outputs, parity with per-pulsar GetTOAs runs,
and multi-host sharding of the (pulsar, archive) grid."""

import numpy as np
import pytest

from pulseportraiture_tpu.io import write_gmodel
from pulseportraiture_tpu.pipeline import (GetTOAs, IPTAJob,
                                           stream_ipta_campaign)
from pulseportraiture_tpu.synth import default_test_model, make_fake_pulsar
from pulseportraiture_tpu.utils.mjd import MJD


@pytest.fixture(scope="module")
def campaign(tmp_path_factory):
    """Three pulsars with DIFFERENT templates/periods/DMs, a few epochs
    each — distinct enough that a model mix-up would be loud."""
    root = tmp_path_factory.mktemp("ipta")
    jobs = []
    specs = [
        ("J0001+01", 0.003, 10.0, 1500.0),
        ("J0002+02", 0.005, 30.0, 1400.0),
        ("J0003+03", 0.002, 55.0, 1600.0),
    ]
    for k, (psr, P0, DM, nu_ref) in enumerate(specs):
        model = default_test_model(nu_ref)
        gmodel = str(root / f"{psr}.gmodel")
        write_gmodel(model, gmodel, quiet=True)
        par = {"PSR": psr, "P0": P0, "DM": DM, "PEPOCH": 55000.0}
        files = []
        for i in range(3):
            p = str(root / f"{psr}_ep{i}.fits")
            make_fake_pulsar(model, par, outfile=p, nsub=2, nchan=16,
                             nbin=128, nu0=nu_ref, bw=600.0,
                             dDM=2e-4 * (i - 1),
                             start_MJD=MJD(55100 + 7 * i + k, 0.15),
                             noise_stds=0.05, dedispersed=False,
                             quiet=True, rng=1000 + 10 * k + i)
            files.append(p)
        jobs.append(IPTAJob(psr, files, gmodel))
    return root, jobs


@pytest.mark.slow
def test_ipta_campaign_matches_per_pulsar_gettoas(campaign, tmp_path):
    """The campaign's TOAs equal what per-pulsar GetTOAs runs produce
    (the VERDICT round-2 done criterion for config 5)."""
    root, jobs = campaign
    res = stream_ipta_campaign(jobs, outdir=str(tmp_path / "tims"),
                               nsub_batch=4, quiet=True)
    assert res.pulsars == [j.pulsar for j in jobs]
    assert len(res.TOA_list) == 3 * 3 * 2  # pulsars x epochs x subints

    for job in jobs:
        gt = GetTOAs(job.datafiles, job.modelfile, quiet=True)
        gt.get_TOAs(quiet=True)
        want = {(t.archive, t.flags["subint"]):
                (t.MJD.tim_string(), t.TOA_error, t.DM)
                for t in gt.TOA_list}
        got = {(t.archive, t.flags["subint"]):
               (t.MJD.tim_string(), t.TOA_error, t.DM)
               for t in res.per_pulsar[job.pulsar].TOA_list}
        assert got.keys() == want.keys()
        for key in want:
            assert got[key][0] == want[key][0]  # digit-exact MJD
            assert got[key][1] == pytest.approx(want[key][1], rel=1e-9)
            assert got[key][2] == pytest.approx(want[key][2], abs=1e-12)
        # per-pulsar DeltaDM summary covers every archive of the job
        means, errs = res.DeltaDM_summary[job.pulsar]
        assert len(means) == len(job.datafiles)
        np.testing.assert_allclose(
            sorted(means), sorted(gt.DeltaDM_means), atol=1e-12)

    # per-pulsar incremental .tim checkpoints on disk, one per pulsar
    tims = sorted(p.name for p in (tmp_path / "tims").iterdir())
    assert tims == sorted(f"{j.pulsar}.tim" for j in jobs)
    for j in jobs:
        lines = (tmp_path / "tims" / f"{j.pulsar}.tim").read_text()
        assert lines.count(j.pulsar[0:1]) >= 1 and len(
            [ln for ln in lines.splitlines() if ln.strip()]) >= 6


@pytest.mark.slow  # ~15 s; per-job option plumbing stays tier-1 via
# the serve lane-key coalescing tests (tests/test_serve.py)
def test_ipta_per_job_option_overrides(campaign, tmp_path):
    """Per-job kwargs override campaign-wide defaults (e.g. one
    scattered pulsar fits tau while the rest do not)."""
    root, jobs = campaign
    # rebuild job 0 with fit_scat on; give it scattered data
    model = default_test_model(1500.0)
    par = {"PSR": "SC", "P0": 0.003, "DM": 10.0, "PEPOCH": 55000.0}
    p = str(tmp_path / "sc0.fits")
    make_fake_pulsar(model, par, outfile=p, nsub=2, nchan=32, nbin=256,
                     nu0=1500.0, bw=800.0, t_scat=3e-4, alpha=-4.0,
                     start_MJD=MJD(55100, 0.1), noise_stds=0.02,
                     dedispersed=False, quiet=True, rng=77)
    gmodel = str(tmp_path / "sc.gmodel")
    write_gmodel(model, gmodel, quiet=True)
    mixed = [IPTAJob("SC", [p], gmodel, fit_scat=True,
                     scat_guess="auto"),
             jobs[1]]
    res = stream_ipta_campaign(mixed, nsub_batch=4, quiet=True)
    sc_toas = res.per_pulsar["SC"].TOA_list
    other = res.per_pulsar[jobs[1].pulsar].TOA_list
    assert all("scat_time" in t.flags for t in sc_toas)
    assert all("scat_time" not in t.flags for t in other)
    # injected tau recovered on the scattered job
    t = sc_toas[0]
    expect_us = 3e-4 * 1e6 * (t.flags["scat_ref_freq"] / 1500.0) \
        ** t.flags["scat_ind"]
    assert t.flags["scat_time"] == pytest.approx(expect_us, rel=0.15)


def test_ipta_duplicate_names_rejected(campaign):
    root, jobs = campaign
    with pytest.raises(ValueError, match="duplicate"):
        stream_ipta_campaign([jobs[0], jobs[0]], quiet=True)


def test_ipta_shard_split_covers_grid(campaign, monkeypatch):
    """With a (monkeypatched) 2-process view, the two shards partition
    the (pulsar, archive) grid and each host still measures every
    pulsar (round-robin balance)."""
    from pulseportraiture_tpu import parallel
    from pulseportraiture_tpu.pipeline import ipta as ipta_mod

    root, jobs = campaign
    results = []
    for fake_pid in (0, 1):
        monkeypatch.setattr(parallel, "process_index", lambda: fake_pid)
        monkeypatch.setattr(parallel, "process_count", lambda: 2)
        monkeypatch.setattr(
            parallel, "shard_files",
            lambda seq, i=fake_pid: list(seq)[i::2])
        monkeypatch.setattr(
            parallel, "process_allgather", lambda x: [np.atleast_1d(x)])
        results.append(stream_ipta_campaign(jobs, nsub_batch=4,
                                            quiet=True))
    got = sorted((t.archive, t.flags["subint"])
                 for r in results for t in r.TOA_list)
    whole = stream_ipta_campaign(jobs, shard=False, nsub_batch=4,
                                 quiet=True)
    want = sorted((t.archive, t.flags["subint"]) for t in whole.TOA_list)
    assert got == want
    for r in results:  # balanced: each host touches all three pulsars
        assert len(r.per_pulsar) == 3
