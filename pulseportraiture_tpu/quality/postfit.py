"""Model-based post-fit channel cut (ISSUE 12 tentpole, layer 2).

The reference flags channels whose per-channel reduced chi^2 or
matched-filter S/N disqualify them AFTER a fit (pptoas.py:1266-1343 /
ppzap's model path): an 8-round loop that re-derives the chi^2 cut
from the median of the surviving channels each round.  GetTOAs used to
run it per subint in host Python; this module holds the pure-array
core — a host NumPy oracle and a batched device twin — so the cut
runs as ONE cheap device pass over an archive's (nsub, nchan) quality
arrays, and ``GetTOAs.get_channels_to_zap`` (and with it ``ppzap -m``)
routes through the shared implementation behind the ``zap_device``
tri-state.

Unlike the median NOISE cut (quality/excision.py), this cut is fully
bit-exact across lanes: its only statistics are a median (exact via
``masked_median_lastaxis``), a multiply by 3, and comparisons — no
reduction-order-dependent sums — so host and device flag lists are
identical by construction, not just gated.
"""

import numpy as np

from .excision import masked_median_lastaxis

__all__ = ["postfit_cut_np", "postfit_cut_mask", "postfit_cut_device"]

_MAX_ROUNDS = 8  # reference pptoas.py:1296 (iterate=True)


def _snr_floor(snr_tot, nchx, SNR_threshold):
    """min(SNR_threshold, sqrt(max(snr_tot, 0)^2 / nchx)) with the
    reference's non-finite fallback — identical fp ops on both lanes
    (max, square, divide, sqrt are all correctly rounded)."""
    snr_tot = np.asarray(snr_tot, float)
    nchx = np.maximum(np.asarray(nchx, float), 1.0)
    cut = np.sqrt(np.maximum(snr_tot, 0.0) ** 2 / nchx)
    cut = np.where(np.isfinite(snr_tot), cut, SNR_threshold)
    return np.minimum(SNR_threshold, cut)


def postfit_cut_np(chan_rchi2, chan_snr, snr_tot, okc_mask,
                   SNR_threshold=8.0, rchi2_threshold=1.3,
                   iterate=True):
    """Host oracle: the reference red-chi^2 / S-N channel cut
    (pptoas.py:1292-1307) vectorized over rows.

    chan_rchi2 / chan_snr / okc_mask: (nsub, nchan); snr_tot: (nsub,).
    Returns a (nsub, nchan) boolean BAD mask (True = zap)."""
    rchi2 = np.asarray(chan_rchi2, float)
    snr = np.asarray(chan_snr, float)
    okc = np.asarray(okc_mask) > 0
    nsub, nchan = rchi2.shape
    floor = _snr_floor(snr_tot, okc.sum(axis=1), SNR_threshold)
    bad_out = np.zeros((nsub, nchan), bool)
    for i in range(nsub):
        oi = np.flatnonzero(okc[i])
        if oi.size == 0:
            continue
        bad = np.zeros(nchan, bool)
        cut = float(rchi2_threshold)
        for _ in range(_MAX_ROUNDS if iterate else 1):
            with np.errstate(invalid="ignore"):
                new_bad = okc[i] & ((rchi2[i] > cut)
                                    | (snr[i] < floor[i]))
            if np.array_equal(new_bad, bad):
                break
            bad = new_bad
            good = oi[~bad[oi]]
            if good.size == 0:
                break
            cut = max(float(rchi2_threshold),
                      float(np.median(rchi2[i, good])) * 3.0)
        bad_out[i] = bad
    return bad_out


def postfit_cut_mask(chan_rchi2, chan_snr, snr_tot, okc_mask,
                     SNR_threshold=8.0, rchi2_threshold=1.3,
                     iterate=True):
    """Traceable batched twin of :func:`postfit_cut_np`: a fixed
    8-round ``fori_loop`` with per-row done flags (a row freezes once
    its bad set stops changing or its survivor set empties — the
    reference's two break conditions).  Bit-identical to the oracle:
    the re-derived cut is ``max(threshold, exact_median * 3)``."""
    import jax.numpy as jnp
    from jax import lax

    rchi2 = jnp.asarray(chan_rchi2)
    snr = jnp.asarray(chan_snr, rchi2.dtype)
    okc = jnp.asarray(okc_mask) > 0
    snr_tot = jnp.asarray(snr_tot, rchi2.dtype)
    thr = rchi2.dtype.type(rchi2_threshold)
    snr_th = rchi2.dtype.type(SNR_threshold)
    nchx = jnp.maximum(jnp.sum(okc, axis=-1), 1).astype(rchi2.dtype)
    floor_ = jnp.sqrt(jnp.maximum(snr_tot, 0.0) ** 2 / nchx)
    floor_ = jnp.where(jnp.isfinite(snr_tot), floor_, snr_th)
    floor_ = jnp.minimum(snr_th, floor_)

    bad0 = jnp.zeros(okc.shape, bool)
    cut0 = jnp.full(okc.shape[:-1], thr)
    done0 = jnp.sum(okc, axis=-1) == 0

    def body(_, st):
        bad, cut, done = st
        base = okc & ((rchi2 > cut[..., None])
                      | (snr < floor_[..., None]))
        same = jnp.all(base == bad, axis=-1)
        new_bad = jnp.where((done | same)[..., None], bad, base)
        done = done | same
        good = okc & ~new_bad
        empty = jnp.sum(good, axis=-1) == 0
        med = masked_median_lastaxis(rchi2, good)
        new_cut = jnp.maximum(thr, med * 3)
        cut = jnp.where(done | empty, cut, new_cut)
        return new_bad, cut, done | empty

    bad, _, _ = lax.fori_loop(0, _MAX_ROUNDS if iterate else 1, body,
                              (bad0, cut0, done0))
    return bad


def postfit_cut_device(chan_rchi2, chan_snr, snr_tot, okc_mask,
                       SNR_threshold=8.0, rchi2_threshold=1.3,
                       iterate=True):
    """One jitted dispatch of :func:`postfit_cut_mask`; host bool
    array out.  NaN rchi2/snr entries (degenerate fits) compare False
    against every cut on both lanes, so they are never flagged —
    matching the host oracle."""
    import jax

    key = ("postfit", bool(iterate))
    fn = _jit_cache.get(key)
    if fn is None:
        fn = _jit_cache[key] = jax.jit(
            postfit_cut_mask,
            static_argnames=("SNR_threshold", "rchi2_threshold",
                             "iterate"))
    bad = fn(chan_rchi2, chan_snr, snr_tot,
             np.asarray(okc_mask) > 0,
             SNR_threshold=float(SNR_threshold),
             rchi2_threshold=float(rchi2_threshold),
             iterate=bool(iterate))
    return np.asarray(bad)


_jit_cache = {}
