"""Continuous-batching TOA service (ISSUE 8): the serving loop must
reproduce the one-shot driver byte-for-byte while coalescing subints
across concurrent requests, honor its deadline/backpressure/drain
contracts, and the satellite passes (manifest AOT warmup, bucket-
lattice padding) must hold their gates."""

import os
import threading

import numpy as np
import pytest

from pulseportraiture_tpu import config, telemetry
from pulseportraiture_tpu.io import write_gmodel
from pulseportraiture_tpu.pipeline import stream_wideband_TOAs
from pulseportraiture_tpu.serve import (AdmissionQueue, ServeRejected,
                                        ServeRequest, ToaClient,
                                        ToaServer)
from pulseportraiture_tpu.synth import default_test_model, make_fake_pulsar
from pulseportraiture_tpu.utils.mjd import MJD

PAR = {"PSR": "J1744-1134", "RAJ": "17:44:29.4", "DECJ": "-11:34:54.6",
       "P0": 0.004074, "PEPOCH": 55000.0, "DM": 3.139}


@pytest.fixture(scope="module")
def campaign(tmp_path_factory):
    root = tmp_path_factory.mktemp("serve")
    model = default_test_model(1500.0)
    gmodel = str(root / "model.gmodel")
    write_gmodel(model, gmodel, quiet=True)
    files = []
    for i in range(4):
        path = str(root / f"ep{i}.fits")
        make_fake_pulsar(model, PAR, outfile=path, nsub=2, nchan=16,
                         nbin=128, nu0=1500.0, bw=400.0, tsub=60.0,
                         phase=0.01 * i, dDM=1e-4,
                         start_MJD=MJD(55100 + i, 0.1), noise_stds=0.08,
                         dedispersed=False, quiet=True, rng=100 + i)
        files.append(path)
    return files, gmodel


def test_serve_concurrent_clients_byte_identical(campaign, tmp_path):
    """The acceptance core: >= 2 client threads submit concurrently,
    their subints COALESCE into shared fused buckets (batch_coalesce
    proves it), and each request's .tim is byte-identical to the
    one-shot driver's checkpoint for the same archives."""
    files, gmodel = campaign
    filesA, filesB = files[:2], files[2:]
    timA1, timB1 = tmp_path / "A1.tim", tmp_path / "B1.tim"
    a1 = stream_wideband_TOAs(filesA, gmodel, nsub_batch=8,
                              tim_out=str(timA1), quiet=True)
    b1 = stream_wideband_TOAs(filesB, gmodel, nsub_batch=8,
                              tim_out=str(timB1), quiet=True)

    trace = str(tmp_path / "serve.jsonl")
    timA2, timB2 = tmp_path / "A2.tim", tmp_path / "B2.tim"
    # max_wait longer than admission so the shared bucket really spans
    # both requests before anything launches (each request alone holds
    # 4 subints of the 8-subint bucket)
    srv = ToaServer(nsub_batch=8, max_wait_ms=500,
                    telemetry=trace).start()
    client = ToaClient(srv)
    results = {}

    def go(tag, fs, tim):
        results[tag] = client.get_TOAs(fs, gmodel, timeout=300,
                                       tim_out=str(tim), name=tag)

    threads = [threading.Thread(target=go, args=("A", filesA, timA2)),
               threading.Thread(target=go, args=("B", filesB, timB2))]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    srv.stop()

    assert timA1.read_bytes() == timA2.read_bytes()
    assert timB1.read_bytes() == timB2.read_bytes()
    for one, served in ((a1, results["A"]), (b1, results["B"])):
        assert len(served.TOA_list) == len(one.TOA_list) == 4
        assert served.order == one.order
        assert served.DeltaDM_means == one.DeltaDM_means
        for ta, tb in zip(one.TOA_list, served.TOA_list):
            assert (ta.MJD.day, ta.MJD.frac) == (tb.MJD.day, tb.MJD.frac)
            assert ta.DM == tb.DM
            assert ta.flags == tb.flags

    manifest, events = telemetry.validate_trace(trace)
    coalesce = [e for e in events if e["type"] == "batch_coalesce"]
    assert coalesce, "server launched no dispatches?"
    # the fused bucket really mixed both requests' subints
    assert max(e["n_requests"] for e in coalesce) >= 2
    done = [e for e in events if e["type"] == "request_done"]
    assert {e["req"] for e in done} == {"A", "B"}
    assert all(e["wall_s"] >= e["queue_s"] >= 0 for e in done)
    import io

    summary = telemetry.report(trace, file=io.StringIO())
    assert summary["n_requests"] == 2
    assert summary["req_p50_s"] is not None
    assert summary["batch_occupancy"] is not None


def test_serve_deadline_flush_partial_bucket(campaign, tmp_path):
    """Continuous batching's latency half: a bucket that can never
    fill (nsub_batch far above the offered subints) launches once its
    oldest subint exceeds serve_max_wait_ms, padded to the shape
    class — the request completes without further traffic."""
    files, gmodel = campaign
    trace = str(tmp_path / "deadline.jsonl")
    with ToaServer(nsub_batch=64, max_wait_ms=30,
                   telemetry=trace) as srv:
        res = srv.submit(files[:1], gmodel, name="D").result(300)
    assert len(res.TOA_list) == 2
    _, events = telemetry.validate_trace(trace)
    co = [e for e in events if e["type"] == "batch_coalesce"]
    assert len(co) == 1
    assert co[0]["rows"] == 2 and co[0]["pad"] == 62  # padded partial


def test_serve_trickle_partial_flushes_byte_identical(campaign, tmp_path):
    """The observatory-ingest arrival shape (ISSUE 18): archives
    trickle in ONE AT A TIME against a bucket they can never fill.
    Every request must launch as its own flush_stale partial bucket
    within the deadline — no cross-archive coalescing to wait for —
    and the admission-ordered concatenation of the per-request .tim
    files must be byte-identical to the one-shot batched driver over
    the finished corpus."""
    files, gmodel = campaign
    ref = tmp_path / "batched.tim"
    stream_wideband_TOAs(files, gmodel, nsub_batch=8,
                         tim_out=str(ref), quiet=True)
    trace = str(tmp_path / "trickle.jsonl")
    tims = [tmp_path / f"t{i}.tim" for i in range(len(files))]
    with ToaServer(nsub_batch=64, max_wait_ms=30,
                   telemetry=trace) as srv:
        client = ToaClient(srv)
        for i, (f, tim) in enumerate(zip(files, tims)):
            # wait for each result before offering the next archive:
            # a genuine trickle, never two archives in one bucket
            res = client.get_TOAs([f], gmodel, timeout=300,
                                  tim_out=str(tim), name=f"t{i}")
            assert len(res.TOA_list) == 2
    streamed = b"".join(t.read_bytes() for t in tims)
    assert streamed == ref.read_bytes()
    _, events = telemetry.validate_trace(trace)
    co = [e for e in events if e["type"] == "batch_coalesce"]
    # one partial bucket per archive, each deadline-flushed solo
    assert len(co) == len(files)
    assert all(e["n_requests"] == 1 and e["rows"] == 2
               and e["pad"] == 62 for e in co)


def test_serve_backpressure_and_closed_rejection(campaign, tmp_path):
    """The admission bound is LOUD: a submit beyond queue_depth
    archives raises ServeRejected with retryable=True (nothing
    enqueued); after stop() the rejection is terminal
    (retryable=False)."""
    files, gmodel = campaign
    srv = ToaServer(nsub_batch=8, max_wait_ms=20, queue_depth=2)
    # a request larger than the WHOLE queue could never fit: terminal
    # rejection (retrying it would spin forever), even on an idle queue
    with pytest.raises(ServeRejected, match="split it") as ei:
        srv.submit(files[:3], gmodel, name="huge")
    assert not ei.value.retryable
    # not started: nothing drains the queue, so the bound is exact
    first = srv.submit(files[:2], gmodel, name="ok")
    with pytest.raises(ServeRejected, match="queue full") as ei:
        srv.submit(files[2:], gmodel, name="shed")
    assert ei.value.retryable
    srv.start()
    res = first.result(300)
    assert len(res.TOA_list) == 4
    srv.stop()
    with pytest.raises(ServeRejected, match="stopping") as ei:
        srv.submit(files[:1], gmodel)
    assert not ei.value.retryable


def test_serve_graceful_drain_completes_outstanding(campaign, tmp_path):
    """stop(drain=True) called right after submit: the request must
    still resolve (queue drains, buckets flush, dispatches drain)
    before stop returns."""
    files, gmodel = campaign
    srv = ToaServer(nsub_batch=64, max_wait_ms=1000).start()
    h = srv.submit(files[:2], gmodel, name="G")
    srv.stop(drain=True)  # long deadline: only the drain flushes it
    assert h.done()
    assert len(h.result(0)
               .TOA_list) == 4


def test_serve_request_error_isolated(campaign, tmp_path):
    """A request with a broken option set fails ITS result; the
    server keeps serving."""
    files, gmodel = campaign
    with ToaServer(nsub_batch=8, max_wait_ms=20) as srv:
        bad = srv.submit(files[:1], gmodel, name="bad",
                         no_such_option=True)
        good = srv.submit(files[:1], gmodel, name="good")
        with pytest.raises(TypeError, match="no_such_option"):
            bad.result(300)
        assert len(good.result(300).TOA_list) == 2


def test_toa_client_map_error_isolated(campaign, tmp_path):
    """ToaClient.map error path (ISSUE 10 satellite): a request that
    fails mid-batch surfaces its error from result() WITHOUT
    poisoning siblings routed to the same host — every good spec
    still returns its full result, and the failure is the original
    exception.  return_errors=True hands the exception back in its
    slot instead of raising."""
    files, gmodel = campaign
    with ToaServer(nsub_batch=8, max_wait_ms=20) as srv:
        client = ToaClient(srv)
        specs = [
            ([files[0]], gmodel, {"name": "ok0"}),
            ([files[1]], gmodel, {"name": "boom",
                                  "no_such_option": True}),
            ([files[2]], gmodel, {"name": "ok1"}),
        ]
        # default: raises the failure, but only after every sibling
        # resolved (nothing left stranded in flight)
        with pytest.raises(TypeError, match="no_such_option"):
            client.map(specs, timeout=300)
        # return_errors: the bad slot carries its exception object,
        # the good slots their DataBunches, in spec order
        out = client.map(specs, timeout=300, return_errors=True)
        assert len(out[0].TOA_list) == 2
        assert isinstance(out[1], TypeError)
        assert len(out[2].TOA_list) == 2
        # the host is not poisoned: a fresh submit still serves
        assert len(client.get_TOAs([files[3]], gmodel,
                                   timeout=300).TOA_list) == 2


@pytest.mark.slow
def test_serve_warmup_manifest_kills_cold_starts(campaign, tmp_path):
    """ROADMAP item 5's tail: AOT warmup from a prior run's trace
    compiles every recorded dispatch shape at server start, and the
    serve trace then records ZERO cold dispatches — with output still
    byte-identical to the one-shot driver."""
    files, gmodel = campaign
    prior = str(tmp_path / "prior.jsonl")
    tim1 = tmp_path / "one.tim"
    stream_wideband_TOAs(files, gmodel, nsub_batch=8,
                         tim_out=str(tim1), quiet=True,
                         telemetry=prior)
    n_shapes = len({e["shape"]
                    for e in telemetry.validate_trace(prior)[1]
                    if e["type"] == "dispatch"})
    assert n_shapes >= 1

    trace = str(tmp_path / "warm.jsonl")
    tim2 = tmp_path / "served.tim"
    with ToaServer(nsub_batch=8, max_wait_ms=20, telemetry=trace,
                   warmup_manifest=prior, warmup_model=gmodel) as srv:
        srv.submit(files, gmodel, name="W",
                   tim_out=str(tim2)).result(300)
    assert tim1.read_bytes() == tim2.read_bytes()

    import io

    import jax

    _, events = telemetry.validate_trace(trace)
    warm = [e for e in events if e["type"] == "warmup_compile"]
    assert len(warm) == n_shapes * len(jax.local_devices())
    disp = [e for e in events if e["type"] == "dispatch"]
    assert disp and not any(e["cold"] for e in disp)
    summary = telemetry.report(trace, file=io.StringIO())
    assert summary["n_cold"] == 0
    assert summary["n_warmup"] == len(warm)


def test_serve_ipta_campaign_thin_client(campaign, tmp_path):
    """stream_ipta_campaign(server=...) routes every pulsar's shard
    through the shared warm server and produces the same per-pulsar
    .tim files as the executor-per-pulsar path."""
    from pulseportraiture_tpu.pipeline import stream_ipta_campaign

    files, gmodel = campaign
    jobs = [("PSRA", files[:2], gmodel), ("PSRB", files[2:], gmodel)]
    out1, out2 = tmp_path / "solo", tmp_path / "served"
    r1 = stream_ipta_campaign(jobs, outdir=str(out1), nsub_batch=8,
                              quiet=True)
    with ToaServer(nsub_batch=8, max_wait_ms=50) as srv:
        r2 = stream_ipta_campaign(jobs, outdir=str(out2), nsub_batch=8,
                                  quiet=True, server=srv)
        with pytest.raises(ValueError, match="resume"):
            stream_ipta_campaign(jobs, outdir=str(out2), resume=True,
                                 quiet=True, server=srv)
        # executor-level knobs are the SERVER's, not lane options —
        # refused by name instead of a TypeError on the serving thread
        with pytest.raises(ValueError, match="max_inflight"):
            stream_ipta_campaign(jobs, outdir=str(out2), quiet=True,
                                 server=srv, max_inflight=8)
    for psr in ("PSRA", "PSRB"):
        assert ((out1 / f"{psr}.tim").read_bytes()
                == (out2 / f"{psr}.tim").read_bytes())
        m1, e1 = r1.DeltaDM_summary[psr]
        m2, e2 = r2.DeltaDM_summary[psr]
        assert np.array_equal(m1, m2) and np.array_equal(e1, e2)
    assert len(r1.TOA_list) == len(r2.TOA_list) == 8


def test_bucket_pad_digit_identity(tmp_path):
    """config.bucket_pad pads a 12-channel layout to the 16-channel
    shape class (trace shapes prove it) with .tim output byte-
    identical on BOTH payload lanes — masked edge-replicated pad
    channels contribute exactly zero."""
    model = default_test_model(1500.0)
    gmodel = str(tmp_path / "m.gmodel")
    write_gmodel(model, gmodel, quiet=True)
    files = []
    for i in range(2):
        p = str(tmp_path / f"np{i}.fits")
        make_fake_pulsar(model, PAR, outfile=p, nsub=2, nchan=12,
                         nbin=128, nu0=1500.0, bw=400.0, tsub=60.0,
                         dDM=1e-4, start_MJD=MJD(55200 + i, 0.1),
                         noise_stds=0.08, dedispersed=False,
                         quiet=True, rng=300 + i)
        files.append(p)
    assert config.bucket_pad is False
    for tscrunch, tag in ((False, "raw"), (True, "dec")):
        tim_e = tmp_path / f"{tag}_exact.tim"
        tim_p = tmp_path / f"{tag}_pad.tim"
        trace = str(tmp_path / f"{tag}_pad.jsonl")
        stream_wideband_TOAs(files, gmodel, nsub_batch=8,
                             tscrunch=tscrunch, tim_out=str(tim_e),
                             quiet=True)
        config.bucket_pad = True
        try:
            stream_wideband_TOAs(files, gmodel, nsub_batch=8,
                                 tscrunch=tscrunch, tim_out=str(tim_p),
                                 quiet=True, telemetry=trace)
        finally:
            config.bucket_pad = False
        assert tim_e.read_bytes() == tim_p.read_bytes(), tag
        shapes = {e["shape"]
                  for e in telemetry.validate_trace(trace)[1]
                  if e["type"] == "dispatch"}
        assert shapes and all(s.startswith("16x128:") for s in shapes)


def test_bucket_pad_resolution_and_env_hook(monkeypatch):
    """bucket_pad_to: next power of two when enabled, identity when
    off; 'auto' pads only on TPU backends; PPT_BUCKET_PAD rides
    env_overrides with the strict tri-state parse."""
    from pulseportraiture_tpu.pipeline.stream import bucket_pad_to

    old = config.bucket_pad
    try:
        config.bucket_pad = False
        assert bucket_pad_to(12) == 12
        config.bucket_pad = True
        assert [bucket_pad_to(n) for n in (1, 2, 12, 16, 17)] == \
            [1, 2, 16, 16, 32]
        config.bucket_pad = "auto"  # tests run on CPU: no padding
        assert bucket_pad_to(12) == 12
        config.bucket_pad = "bananas"
        with pytest.raises(ValueError, match="bucket_pad"):
            bucket_pad_to(12)
        monkeypatch.setenv("PPT_BUCKET_PAD", "on")
        assert "bucket_pad" in config.env_overrides()
        assert config.bucket_pad is True
        monkeypatch.setenv("PPT_BUCKET_PAD", "nope")
        with pytest.raises(ValueError, match="PPT_BUCKET_PAD"):
            config.env_overrides()
    finally:
        config.bucket_pad = old


def test_serve_env_hooks(monkeypatch):
    """PPT_SERVE_MAX_WAIT_MS / PPT_SERVE_QUEUE_DEPTH: strict parses,
    loud errors, registered in KNOWN_PPT_ENV."""
    old = (config.serve_max_wait_ms, config.serve_queue_depth)
    try:
        for name in ("PPT_SERVE_MAX_WAIT_MS", "PPT_SERVE_QUEUE_DEPTH",
                     "PPT_BUCKET_PAD"):
            assert name in config.KNOWN_PPT_ENV
        monkeypatch.setenv("PPT_SERVE_MAX_WAIT_MS", "125.5")
        monkeypatch.setenv("PPT_SERVE_QUEUE_DEPTH", "9")
        changed = config.env_overrides()
        assert "serve_max_wait_ms" in changed
        assert "serve_queue_depth" in changed
        assert config.serve_max_wait_ms == 125.5
        assert config.serve_queue_depth == 9
        monkeypatch.setenv("PPT_SERVE_MAX_WAIT_MS", "-1")
        with pytest.raises(ValueError, match="PPT_SERVE_MAX_WAIT_MS"):
            config.env_overrides()
        monkeypatch.setenv("PPT_SERVE_MAX_WAIT_MS", "50")
        monkeypatch.setenv("PPT_SERVE_QUEUE_DEPTH", "0")
        with pytest.raises(ValueError, match="PPT_SERVE_QUEUE_DEPTH"):
            config.env_overrides()
    finally:
        (config.serve_max_wait_ms, config.serve_queue_depth) = old


def test_admission_queue_units():
    """Queue accounting: the bound counts archives, release returns
    credit, close makes submits terminal and drain empties."""
    q = AdmissionQueue(3)
    r1 = ServeRequest(["a.fits", "b.fits"], "m.gmodel")
    r2 = ServeRequest(["c.fits", "d.fits"], "m.gmodel")
    q.submit(r1)
    assert q.pending_archives == 2
    with pytest.raises(ServeRejected, match="queue full"):
        q.submit(r2)
    q.release(1)
    q.submit(r2)  # 1 + 2 <= 3 now
    assert q.get(0.01) is r1
    assert q.get(0.01) is r2
    assert q.get(0.01) is None  # empty -> timeout
    # credit returns only via release (the server's admission), never
    # via get: popping a request does not mean its archives were
    # prepared yet
    assert q.pending_archives == 3
    q.release(3)
    q.submit(ServeRequest(["e.fits"], "m.gmodel"))
    q.close()
    with pytest.raises(ServeRejected, match="closed"):
        q.submit(ServeRequest(["f.fits"], "m.gmodel"))
    assert len(q.drain()) == 1
    with pytest.raises(ValueError, match="empty"):
        ServeRequest([], "m.gmodel")


def test_parse_shape_key_roundtrip():
    """parse_shape_key inverts _bucket_shape for every bucket
    geometry the dispatcher emits, and refuses garbage loudly."""
    from pulseportraiture_tpu.pipeline.stream import (_Bucket,
                                                      _bucket_shape,
                                                      parse_shape_key)

    freqs = np.linspace(1400.0, 1600.0, 12)
    cases = [
        dict(kind="dec", raw_code="i16", pol_sum=False,
             flags=(True, True, False, False, False)),
        dict(kind="raw", raw_code="i16", pol_sum=False,
             flags=(True, True, False, True, True)),
        dict(kind="raw", raw_code="u8", pol_sum=True,
             flags=(True, False, False, False, False)),
        dict(kind="raw", raw_code="f32", pol_sum=False, flags=()),
    ]
    for c in cases:
        b = _Bucket(freqs, 128, None, c["flags"], kind=c["kind"],
                    raw_code=c["raw_code"], pol_sum=c["pol_sum"])
        spec = parse_shape_key(_bucket_shape(b))
        assert spec["nchan"] == 12 and spec["nbin"] == 128
        assert spec["kind"] == c["kind"]
        assert spec["pol_sum"] == c["pol_sum"]
        if c["kind"] == "raw":
            assert spec["raw_code"] == c["raw_code"]
        assert spec["flags"] == (c["flags"] or None)
    for bad in ("x128:dec", "12x128:wat", "12x128:raw:zzz",
                "12x128:dec:12"):
        with pytest.raises(ValueError):
            parse_shape_key(bad)
