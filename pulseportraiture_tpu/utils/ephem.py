"""Host-side low-precision astronomical ephemerides.

Provides the two site-geometry quantities the reference obtained from
PSRCHIVE's ephemeris engine and that PSRFITS itself does not store:

- per-subint barycentric Doppler factors (reference pplib.py:2795-2805:
  ``doppler_factor = nu_source/nu_observed = sqrt((1+beta)/(1-beta))``
  with beta = v/c and v > 0 for increasing distance), used by the
  pipeline as DM *= df, GM *= df**3 (reference pptoas.py:583-591);
- per-subint parallactic angles (reference pplib.py:2806-2808, via
  PSRCHIVE's ``fix pointing``).

Accuracy budget: the Doppler correction is a ~1e-4 relative effect on
DM, so an Earth-velocity model good to ~10 m/s (|v| ~ 30 km/s ->
3e-4 relative) leaves a < 1e-7 relative DM error — far below TOA
noise.  The model used here is the two-body EMB orbit from the
low-precision solar-position series (mean elements + equation of
centre), precessed to J2000, differentiated analytically by central
difference, plus the Earth-rotation term at the site.  Everything is
plain vectorized NumPy on host: this runs once per archive load, is
not a hot path, and must not touch the accelerator.
"""

import math
import re

import numpy as np

__all__ = [
    "parse_ra", "parse_dec", "radec_unit_vector", "itrf_to_geodetic",
    "gmst_rad", "earth_ssb_velocity_kms", "site_rotation_velocity_kms",
    "doppler_factors", "parallactic_angles", "telescope_itrf",
]

C_KMS = 299792.458          # speed of light [km/s]
AU_KM = 1.495978707e8       # astronomical unit [km]
OMEGA_EARTH = 7.2921150e-5  # Earth sidereal rotation rate [rad/s]
SECPERDAY = 86400.0
# WGS84
_WGS84_A = 6378.137         # equatorial radius [km]
_WGS84_F = 1.0 / 298.257223563

# Mean obliquity of the ecliptic at J2000 [rad]
_EPS0 = math.radians(23.43929111)

# ITRF (x, y, z) [m] for common observatories, keyed by the canonical
# tempo2 site name used in io/telescopes.py.  Values are the published
# tempo2 observatories.dat coordinates (public constants; the numbers
# ARE the spec).  Used only when the archive carries no ANT_X/Y/Z.
_TELESCOPE_ITRF_M = {
    "gbt": (882589.65, -4924872.32, 3943729.348),
    "arecibo": (2390490.0, -5564764.0, 1994727.0),
    "pks": (-4554231.5, 2816759.1, -3454036.3),
    "jb": (3822626.04, -154105.65, 5086486.04),
    "jbmk2": (3822846.76, -153802.28, 5086285.9),
    "eff": (4033949.5, 486989.4, 4900430.8),
    "ncy": (4324165.81, 165927.11, 4670132.83),
    "wsrt": (3828445.659, 445223.6, 5064921.5677),
    "fast": (-1668557.0, 5506838.0, 2744934.0),
    "gmrt": (1656342.3, 5797947.77, 2073243.16),
    "chime": (-2059166.313, -3621302.972, 4814304.113),
    "vla": (-1601192.0, -5041981.4, 3554871.4),
    "srt": (4865182.766, 791922.689, 4035137.174),
    "hart": (5085442.78, 2668263.483, -2768697.034),
    "hobart": (-3950077.96, 2522377.31, -4311667.52),
    "meerkat": (5109360.133, 2006852.586, -3238948.127),
    "lofar": (3826577.462, 461022.624, 5064892.526),
    "mwa": (-2559454.08, 5095372.14, -2849057.18),
    "lwa1": (-1602196.6, -5042313.47, 3553971.51),
    "utr-2": (3307865.236, 2487350.541, 4836939.784),
}


def telescope_itrf(name):
    """ITRF (x, y, z) [m] for a telescope name/alias, or None.
    Prefers a TEMPO2 runtime's observatory table, then the builtin."""
    if not name:
        return None
    from ..io.telescopes import canonical_name, tempo2_itrf

    xyz = tempo2_itrf(name)
    if xyz is None:
        canon = canonical_name(name)
        key = (canon or str(name)).lower()
        xyz = _TELESCOPE_ITRF_M.get(key)
    return np.asarray(xyz, np.float64) if xyz is not None else None


# -- angles -----------------------------------------------------------------

_SEXA = re.compile(r"^([+-]?)(\d+)[:h ](\d+)[:m ]([\d.]+)s?$")


def _parse_sexagesimal(s):
    s = str(s).strip()
    m = _SEXA.match(s)
    if m is None:
        return float(s)  # already decimal
    sign = -1.0 if m.group(1) == "-" else 1.0
    d, mi, se = float(m.group(2)), float(m.group(3)), float(m.group(4))
    return sign * (d + mi / 60.0 + se / 3600.0)


def parse_ra(s):
    """RA 'hh:mm:ss.s' (or decimal degrees) -> degrees."""
    v = _parse_sexagesimal(s)
    return v * 15.0 if _SEXA.match(str(s).strip()) else v


def parse_dec(s):
    """DEC '+dd:mm:ss.s' (or decimal degrees) -> degrees."""
    return _parse_sexagesimal(s)


def radec_unit_vector(ra_deg, dec_deg):
    """J2000 equatorial unit vector toward (RA, DEC)."""
    ra = math.radians(float(ra_deg))
    dec = math.radians(float(dec_deg))
    return np.array([
        math.cos(dec) * math.cos(ra),
        math.cos(dec) * math.sin(ra),
        math.sin(dec),
    ])


def itrf_to_geodetic(xyz_m):
    """ITRF (x, y, z) [m] -> (geodetic latitude [rad], east longitude
    [rad], height [km]) on WGS84 (Bowring's closed-form iteration)."""
    x, y, z = (float(v) / 1000.0 for v in xyz_m)  # km
    a, f = _WGS84_A, _WGS84_F
    b = a * (1.0 - f)
    e2 = f * (2.0 - f)
    ep2 = e2 / (1.0 - e2)
    p = math.hypot(x, y)
    lon = math.atan2(y, x)
    theta = math.atan2(z * a, p * b)
    lat = math.atan2(z + ep2 * b * math.sin(theta) ** 3,
                     p - e2 * a * math.cos(theta) ** 3)
    n = a / math.sqrt(1.0 - e2 * math.sin(lat) ** 2)
    h = p / math.cos(lat) - n
    return lat, lon, h


def gmst_rad(mjd_ut):
    """Greenwich mean sidereal time [rad] at UT MJD (IAU 1982; ~0.1 s
    accuracy — ample for 0.1-degree parallactic angles and mm/s site
    velocities)."""
    mjd = np.asarray(mjd_ut, np.float64)
    d = mjd - 51544.5  # days since J2000.0
    gmst_deg = 280.46061837 + 360.98564736629 * d
    t = d / 36525.0
    gmst_deg = gmst_deg + (0.000387933 - t / 38710000.0) * t * t
    return np.deg2rad(np.mod(gmst_deg, 360.0))


# -- Earth barycentric velocity --------------------------------------------

def _emb_position_au(mjd_tt):
    """EMB heliocentric position [AU], J2000 equatorial frame.

    Low-precision solar series (mean longitude + equation of centre,
    ~0.01 deg), precessed from the mean equinox of date to J2000."""
    t = (np.asarray(mjd_tt, np.float64) - 51544.5) / 36525.0
    L0 = 280.46646 + 36000.76983 * t + 0.0003032 * t * t
    M = np.deg2rad(357.52911 + 35999.05029 * t - 0.0001537 * t * t)
    e = 0.016708634 - 0.000042037 * t
    C = ((1.914602 - 0.004817 * t - 0.000014 * t * t) * np.sin(M)
         + (0.019993 - 0.000101 * t) * np.sin(2.0 * M)
         + 0.000289 * np.sin(3.0 * M))
    lam_sun = L0 + C                       # Sun true longitude, of date
    nu = M + np.deg2rad(C)                 # true anomaly
    R = 1.000001018 * (1.0 - e * e) / (1.0 + e * np.cos(nu))  # [AU]
    # precess longitude of date -> J2000 (general precession 5029"/cy)
    lam = np.deg2rad(lam_sun - 1.39697137 * t)
    # Earth is opposite the Sun; ecliptic latitude ~< 1.2" ignored
    x_ecl = -R * np.cos(lam)
    y_ecl = -R * np.sin(lam)
    ce, se = math.cos(_EPS0), math.sin(_EPS0)
    return np.stack(
        [x_ecl, y_ecl * ce, y_ecl * se], axis=-1)


def earth_ssb_velocity_kms(mjd_tt):
    """Earth barycentric velocity [km/s], J2000 equatorial, at TT MJD
    (UTC is fine: a 69 s timescale offset moves the velocity by mm/s).

    Central difference of the analytic EMB orbit.  Omits the Sun's
    barycentric motion (~13 m/s) and the Earth-Moon wobble (~13 m/s):
    both are < 1e-3 of |v| and contribute < 1e-7 relative DM error.
    Returns shape (..., 3)."""
    dt = 0.02  # days
    mjd = np.asarray(mjd_tt, np.float64)
    dpos = _emb_position_au(mjd + dt) - _emb_position_au(mjd - dt)
    return dpos * (AU_KM / (2.0 * dt * SECPERDAY))


def site_rotation_velocity_kms(mjd_ut, xyz_itrf_m):
    """Observatory velocity [km/s] from Earth rotation, J2000
    equatorial frame: omega x r with r the ITRF position rotated to the
    celestial frame by GMST (polar motion / nutation ~0.1 m/s ignored).
    Returns shape (..., 3)."""
    g = gmst_rad(mjd_ut)
    x, y = float(xyz_itrf_m[0]) / 1000.0, float(xyz_itrf_m[1]) / 1000.0
    cg, sg = np.cos(g), np.sin(g)
    # r_cel = Rz(gmst) r_itrf; v = omega ez x r_cel
    rx = x * cg - y * sg
    ry = x * sg + y * cg
    vx = -OMEGA_EARTH * ry
    vy = OMEGA_EARTH * rx
    return np.stack([vx, vy, np.zeros_like(vx)], axis=-1)


def doppler_factors(mjd_utc, ra_deg, dec_deg, xyz_itrf_m=None):
    """Barycentric Doppler factor nu_source/nu_observed per epoch.

    df = sqrt((1+beta)/(1-beta)), beta = v_r/c with v_r the line-of-
    sight velocity of the observatory away from the source (reference
    convention, pplib.py:2795-2805).  mjd_utc may be an array."""
    n_hat = radec_unit_vector(ra_deg, dec_deg)
    v = earth_ssb_velocity_kms(mjd_utc)
    if xyz_itrf_m is not None:
        v = v + site_rotation_velocity_kms(mjd_utc, xyz_itrf_m)
    beta = -(v @ n_hat) / C_KMS  # receding > 0
    return np.sqrt((1.0 + beta) / (1.0 - beta))


def parallactic_angles(mjd_utc, ra_deg, dec_deg, xyz_itrf_m):
    """Parallactic angle [deg] per epoch at an ITRF site.

    q = atan2(sin H, tan(lat) cos(dec) - sin(dec) cos H), H the local
    hour angle — the standard alt-az formula, matching PSRCHIVE's
    pointing computation (reference pplib.py:2806-2808) to well under
    0.1 deg for UT1-UTC < 1 s."""
    lat, lon_east, _ = itrf_to_geodetic(xyz_itrf_m)
    ra = math.radians(float(ra_deg))
    dec = math.radians(float(dec_deg))
    lst = gmst_rad(mjd_utc) + lon_east
    H = lst - ra
    q = np.arctan2(np.sin(H),
                   math.tan(lat) * math.cos(dec)
                   - math.sin(dec) * np.cos(H))
    return np.rad2deg(q)
