"""Benchmark-rot guard (ISSUE 1 satellite): every benchmarks/bench_*.py
script runs end-to-end at a tiny CPU-safe shape and prints a parseable
JSON line.  The bench scripts had no test coverage at all, so an engine
refactor could silently break the measurement tooling the performance
history depends on."""

import glob
import importlib
import json
import os

import pytest

from pulseportraiture_tpu import config

BENCH_DIR = os.path.join(os.path.dirname(__file__), "..", "benchmarks")
BENCH_MODULES = sorted(
    os.path.basename(p)[:-3]
    for p in glob.glob(os.path.join(BENCH_DIR, "bench_*.py")))

# tiny CPU-safe shapes per script (env knobs each script reads)
TINY_ENV = {
    "bench_scatter": {"PPT_NB": "4", "PPT_NCHAN": "16",
                      "PPT_NBIN": "128"},
    "bench_device_campaign": {"PPT_NSUBB": "4", "PPT_NCHAN": "16",
                              "PPT_NBIN": "128"},
    "bench_align": {"PPT_NE": "4", "PPT_NCHAN": "16", "PPT_NBIN": "128"},
    "bench_noisy_template": {"PPT_NB": "4", "PPT_NCHAN": "16",
                             "PPT_NBIN": "256"},
    # ISSUE 9: the template-factory A/B runs its production arm (one
    # ppgauss subprocess per pulsar) and batched arm (one ppfactory
    # subprocess) plus the in-process oracle digit gate and the
    # resid/jacobian/solve/select attribution at tiny shapes
    "bench_gauss": {"PPT_NPSR": "2", "PPT_NCHAN": "8",
                    "PPT_NBIN": "64", "PPT_NGAUSS": "2",
                    "PPT_NITER": "0", "PPT_GAUSS_CACHE": ""},
    "bench_stream": {"PPT_NARCH": "2", "PPT_NSUB": "2",
                     "PPT_NCHAN": "16", "PPT_NBIN": "128",
                     # multi-device mode: the suite runs with 8
                     # virtual CPU devices, so the 1->2 sweep really
                     # exercises the round-robin executor
                     "PPT_DEVICES": "2",
                     # telemetry rides along (resolved to a tmp path
                     # below): the emitted trace must validate against
                     # the schema, so event-shape drift in the
                     # executor fails HERE, not in a user's campaign
                     "PPT_TELEMETRY": ""},
    "bench_campaign": {"PPT_NARCH": "2", "PPT_NSUB": "2",
                       "PPT_NCHAN": "16", "PPT_NBIN": "128",
                       "PPT_CAMPAIGN_CACHE": "",
                       # ISSUE 6: the link-bound bench runs its
                       # depth-1-vs-N transfer-pipeline A/B under
                       # telemetry; the emitted h2d events must
                       # validate against the schema so copy-stage
                       # drift fails in CI
                       "PPT_TELEMETRY": ""},
    "bench_ipta": {"PPT_NPSR": "1", "PPT_NARCH": "2", "PPT_NSUB": "2",
                   "PPT_NCHAN": "16", "PPT_NBIN": "128"},
    "bench_serve": {"PPT_NARCH": "2", "PPT_NSUB": "2",
                    "PPT_NCHAN": "16", "PPT_NBIN": "128",
                    "PPT_NREQ": "2", "PPT_CAMPAIGN_CACHE": "",
                    # ISSUE 8: the serve arm traces request lifecycle
                    # + batch_coalesce occupancy; the emitted trace
                    # must validate so serve-event drift fails in CI
                    "PPT_TELEMETRY": ""},
    # ISSUE 10: the 1->2 emulated-host router sweep — placement,
    # retry ledger, and per-request .tim identity vs the one-shot
    # references all assert inside the bench; the traces are
    # re-validated here so route-event drift fails in CI (the 1.8x
    # link-scaling gate belongs to real PPT_TUNNEL_EMU bench runs).
    # ISSUE 13 rides along at H=2: the kill-one-host failover arm
    # (zero lost requests, zero duplicated .tim lines, bounded p99),
    # the no-shared-fs codec-lane byte gate, and the hedging on/off
    # byte gate — all ENFORCED inside the bench at every shape
    "bench_router": {"PPT_NARCH": "2", "PPT_NSUB": "2",
                     "PPT_NCHAN": "16", "PPT_NBIN": "128",
                     "PPT_NREQ": "2", "PPT_NHOSTS": "2",
                     "PPT_CAMPAIGN_CACHE": "", "PPT_TELEMETRY": ""},
    # ISSUE 11: the fleet timing A/B — serial-vs-batched GLS solve
    # dispatches over a tiny mixed ELL1/BT/isolated fleet, with the
    # <= 1e-10 batched-vs-host digit gate ENFORCED inside the bench at
    # every shape (including this one), and the emitted trace's
    # timing_fit/fleet_end events schema-validated
    "bench_gls": {"PPT_NPSR": "4", "PPT_NE": "4", "PPT_TELEMETRY": ""},
    # ISSUE 17: the content-addressed result cache — the hit-identity,
    # all-hits, and one-byte-perturbation-miss gates are ENFORCED
    # inside the bench at every shape (the >= 5x Zipf-replay speedup
    # gate belongs to real bench runs: PPT_CACHE_SPEEDUP_GATE=0 here),
    # and the server + router cache traces are re-validated below
    "bench_cache": {"PPT_NARCH": "3", "PPT_NSUB": "2",
                    "PPT_NCHAN": "16", "PPT_NBIN": "64",
                    "PPT_NREQ": "6", "PPT_NHOSTS": "2",
                    "PPT_CACHE_SPEEDUP_GATE": "0",
                    "PPT_CAMPAIGN_CACHE": "", "PPT_TELEMETRY": ""},
    # ISSUE 12: the inline-device vs host-offline excision A/B — the
    # flagged-channel-list digit gate, the ground-truth recovery gate,
    # the inline-vs-oracle .tim byte gate, and the clean-corpus no-op
    # gate are all ENFORCED inside the bench at every shape, and the
    # emitted zap_apply ledger is schema-validated
    "bench_zap": {"PPT_NARCH": "2", "PPT_NSUB": "2",
                  "PPT_NCHAN": "32", "PPT_NBIN": "128",
                  "PPT_TELEMETRY": ""},
    # ISSUE 18: the online observatory pipeline e2e — streamed-vs-
    # offline .tim byte identity, both injected events alerted at
    # their true epochs, zero false alarms on the clean control, and
    # the <= 1e-10 incremental-vs-batch parity are all ENFORCED inside
    # the bench at every shape (the admit->TOA p99 latency gate
    # belongs to real bench runs: PPT_INGEST_P99_GATE unset here)
    "bench_ingest": {"PPT_NARCH": "6", "PPT_NSUB": "2",
                     "PPT_NCHAN": "16", "PPT_NBIN": "128",
                     "PPT_NSEEDS": "2", "PPT_CAMPAIGN_CACHE": "",
                     "PPT_TELEMETRY": ""},
    # ISSUE 19: the per-backend autotune sweep — the >= 1.0x tuned-
    # speedup no-regression gate, the campaign-wide .tim byte gate
    # across the identity knob tier, the warm-DB zero-resweep witness,
    # and the fast/slow fleet's cost-model-vs-least-loaded makespan
    # gate are all ENFORCED inside the bench at every shape
    "bench_autotune": {"PPT_NARCH": "3", "PPT_NSUB": "2",
                       "PPT_NCHAN": "16", "PPT_NBIN": "64",
                       "PPT_NREQ": "2", "PPT_TUNE_NRUN": "1",
                       "PPT_SLOW_MS": "60",
                       "PPT_CAMPAIGN_CACHE": "", "PPT_TELEMETRY": ""},
    # ISSUE 20: the observability on-vs-off A/B — the .tim byte gate
    # and the 100% cross-host merge-reconstruction gate are ENFORCED
    # inside the bench at every shape (the <= 3% wall-overhead gate
    # belongs to real bench runs: per-request jitter at tiny CPU
    # shapes dwarfs the registry cost, so PPT_OBS_OVERHEAD_GATE=0)
    "bench_obs": {"PPT_NARCH": "2", "PPT_NSUB": "2",
                  "PPT_NCHAN": "16", "PPT_NBIN": "128",
                  "PPT_NREQ": "2", "PPT_NHOSTS": "2",
                  "PPT_OBS_OVERHEAD_GATE": "0",
                  "PPT_CAMPAIGN_CACHE": "", "PPT_TELEMETRY": ""},
}

_CONFIG_KEYS = ("dft_precision", "cross_spectrum_dtype", "dft_fold",
                "scatter_compensated", "fit_harmonic_window",
                "telemetry_path", "fit_fused", "fit_pallas",
                "fused_block", "lm_jacobian",
                "raw_subbyte", "transport_compress",
                "result_cache", "cache_dir", "cache_max_mb",
                "tune_db", "autotune", "tune_numerics",
                "lm_compact_every", "stream_pipeline_depth",
                "bucket_pad")

# the heavyweight smoke shapes (tier-1 lives under a wall-clock cap on
# a single-core runner; these dominated the suite's durations report)
# — still exercised in the full `-m slow` run.  bench_ingest's e2e
# gates are mirrored in tier-1 by tests/test_ingest.py +
# tests/test_incremental.py; bench_cache's by tests/test_cache.py.
_HEAVY_BENCHES = {"bench_gauss", "bench_scatter", "bench_zap",
                  "bench_campaign", "bench_ingest", "bench_cache"}


def test_all_bench_scripts_covered():
    """A new bench script must register a tiny shape here or the rot
    guard silently stops covering it."""
    assert set(BENCH_MODULES) == set(TINY_ENV), (
        set(BENCH_MODULES) ^ set(TINY_ENV))


@pytest.mark.parametrize(
    "name",
    [pytest.param(n, marks=pytest.mark.slow) if n in _HEAVY_BENCHES
     else n for n in BENCH_MODULES])
def test_bench_smoke(name, monkeypatch, capsys, tmp_path):
    for k, v in TINY_ENV[name].items():
        if k in ("PPT_CAMPAIGN_CACHE", "PPT_GAUSS_CACHE"):
            v = str(tmp_path / "cache")
        elif k == "PPT_TELEMETRY":
            v = str(tmp_path / "trace.jsonl")
        monkeypatch.setenv(k, v)
    saved = {k: getattr(config, k) for k in _CONFIG_KEYS}
    mod = importlib.import_module(f"benchmarks.{name}")
    try:
        mod.main()
    finally:
        for k, v in saved.items():
            setattr(config, k, v)
    lines = [ln for ln in capsys.readouterr().out.splitlines()
             if ln.strip().startswith("{")]
    assert lines, f"{name} printed no JSON line"
    out = json.loads(lines[-1])
    assert "metric" in out and "value" in out and "unit" in out
    assert out["value"] > 0
    if name == "bench_stream":
        # ISSUE 4: the reworked streaming bench must emit the 1->N
        # scaling table with per-stage attribution of the serialized
        # lane (structural check — throughput gates belong to real
        # bench runs, not tiny CPU smoke shapes)
        assert [r["devices"] for r in out["scaling"]] == [1, 2]
        assert all(r["toas_per_sec"] > 0 for r in out["scaling"])
        assert all("efficiency" in r and "speedup" in r
                   for r in out["scaling"])
        for stage in ("load", "stack", "h2d", "fit", "scatter",
                      "assemble"):
            assert f"stage_{stage}_ms" in out, stage
        assert out["attributed_frac"] > 0
        assert "scaling_ok" in out and "attrib_ok" in out
        # ISSUE 5: the bench ran its sweep with telemetry enabled
        # (PPT_TELEMETRY above) — the emitted trace must validate
        # against the schema, so executor/event-shape drift is caught
        # by CI the moment it lands
        from pulseportraiture_tpu import telemetry

        trace = str(tmp_path / "trace.jsonl")
        assert os.path.exists(trace), "bench_stream emitted no trace"
        manifest, events = telemetry.validate_trace(trace)
        assert manifest["run"] == "stream_wideband_TOAs"
        etypes = {e["type"] for e in events}
        for needed in ("dispatch", "drain", "quality",
                       "archive_prepare", "run_end"):
            assert needed in etypes, needed
        dispatches = [e for e in events if e["type"] == "dispatch"]
        last_run = [e for e in events if e["type"] == "run_end"][-1]
        assert len(dispatches) >= last_run["nfit"]
    if name == "bench_serve":
        # ISSUE 8: the offered-load sweep must report both arms with
        # latency percentiles, and the serve traces must schema-
        # validate with the request lifecycle + coalesce events (the
        # 1.1x throughput gate belongs to real bench runs — a tiny
        # CPU shape pays the whole bucket deadline per dispatch)
        assert out["oneshot_toas_per_sec"] > 0
        assert out["serve_vs_oneshot"] > 0
        assert [a["concurrency"] for a in out["sweep"]] == [1, 2]
        for arm in out["sweep"]:
            assert arm["toas_per_sec"] > 0
            assert arm["p99_s"] >= arm["p50_s"] > 0
            assert arm["batch_occupancy"] is not None
        from pulseportraiture_tpu import telemetry

        for conc in ("1", "2"):
            trace = str(tmp_path / "trace.jsonl") + f".serve{conc}"
            assert os.path.exists(trace), f"no serve{conc} trace"
            manifest, events = telemetry.validate_trace(trace)
            assert manifest["run"] == "ppserve"
            etypes = {e["type"] for e in events}
            for needed in ("serve_start", "request_submit",
                           "request_done", "batch_coalesce",
                           "dispatch", "drain", "serve_stop"):
                assert needed in etypes, needed
            done = [e for e in events if e["type"] == "request_done"]
            assert len(done) == int(conc)
    if name == "bench_router":
        # ISSUE 10: both fleet sizes must report, per-request .tim
        # output must be byte-identical to the one-shot references,
        # requests must land on BOTH emulated hosts at H=2, and the
        # routing traces must schema-validate with the route ledger
        assert out["tim_identical"] is True
        assert out["oneshot_toas_per_sec"] > 0
        assert out["router_speedup"] > 0
        assert [a["hosts"] for a in out["sweep"]] == [1, 2]
        for arm in out["sweep"]:
            assert arm["toas_per_sec"] > 0
            assert arm["n_toas"] == out["toas"]
            assert arm["router_imbalance"] is not None
        two = out["sweep"][-1]
        assert len(two["placement"]) == 2, (
            f"requests did not shard across both hosts: "
            f"{two['placement']}")
        assert sum(two["placement"].values()) == 2  # archives total
        from pulseportraiture_tpu import telemetry

        for H in ("1", "2"):
            trace = str(tmp_path / "trace.jsonl") + f".h{H}"
            assert os.path.exists(trace), f"no h{H} trace"
            manifest, events = telemetry.validate_trace(trace)
            assert manifest["run"] == "pproute"
            etypes = {e["type"] for e in events}
            for needed in ("router_start", "route_submit",
                           "route_done"):
                assert needed in etypes, needed
            done = [e for e in events if e["type"] == "route_done"]
            assert len(done) == 2
            assert all(e["error"] is None for e in done)
            hosts = {e["host"]
                     for e in events if e["type"] == "route_submit"}
            assert len(hosts) == int(H)
        # ISSUE 13: the elastic-fleet arms' gates (enforced inside
        # the bench too — re-checked structurally here so a silently
        # skipped arm fails CI)
        fleet = out["fleet"]
        assert fleet is not None
        assert fleet["failover_ok"] is True
        assert fleet["lost_requests"] == 0
        assert fleet["duplicated_tim_lines"] == 0
        assert fleet["tim_identical"] is True
        assert fleet["p99_bounded"] is True
        assert out["codec_tim_identical"] is True
        assert out["hedge_tim_identical"] is True
        assert out["n_hedge"] >= 1
        # the .fleet trace must carry the health/failover ledger with
        # a schema-valid event stream
        trace = str(tmp_path / "trace.jsonl") + ".fleet"
        assert os.path.exists(trace), "no fleet trace"
        manifest, events = telemetry.validate_trace(trace)
        etypes = {e["type"] for e in events}
        assert "fleet_transition" in etypes
        dead = [e for e in events if e["type"] == "fleet_transition"
                and e["to_state"] == "DEAD"]
        assert dead and dead[0]["host"] == "k0"
        if fleet["killed_host_requests"]:
            assert "route_failover" in etypes
        # ISSUE 17: the kill-during-hit arm — the whole request set
        # served from the router's result cache after host0 died, no
        # re-placement, no failover, byte-identical (enforced in the
        # bench; re-checked structurally so a skipped arm fails CI)
        chit = out["kill_during_hit"]
        assert chit is not None
        assert chit["lost_requests"] == 0
        assert chit["replaced_work"] is False
        assert chit["tim_identical"] is True
        assert chit["cache_hits"] == 2  # == PPT_NREQ
        trace = str(tmp_path / "trace.jsonl") + ".chit"
        assert os.path.exists(trace), "no kill-during-hit trace"
        manifest, events = telemetry.validate_trace(trace)
        etypes = {e["type"] for e in events}
        assert "cache_hit" in etypes
        assert "route_failover" not in etypes
    if name == "bench_autotune":
        # ISSUE 19: the no-regression + byte-identity + zero-resweep +
        # fleet-placement gates are enforced inside the bench (assert/
        # SystemExit on violation) — re-checked structurally here so a
        # silently skipped arm fails CI, and the reuse trace must
        # schema-validate with the db_hit witness
        assert out["speedup_ok"] is True
        assert out["value"] >= 1.0  # tuned speedup, never a slowdown
        assert out["tim_identical"] is True
        assert out["db_reuse_ok"] is True
        assert out["resweeps_on_warm_db"] == 0
        assert out["n_swept"] > 0
        assert out["fingerprint"]
        fleet = out["fleet"]
        assert fleet is not None
        assert fleet["cost_ok"] is True
        assert fleet["lost_requests"] == 0
        assert fleet["fleet_tim_identical"] is True
        # the slow host's measured TOAs/s must really be slower — the
        # signal the cost model places by
        assert fleet["toas_per_s"][1] < fleet["toas_per_s"][0]
        from pulseportraiture_tpu import telemetry

        for suffix, hit in ((".tune1", False), (".tune2", True)):
            trace = str(tmp_path / "trace.jsonl") + suffix
            assert os.path.exists(trace), f"no {suffix} trace"
            _manifest, events = telemetry.validate_trace(trace)
            applies = [e for e in events if e["type"] == "tune_apply"]
            assert [e["db_hit"] for e in applies] == [hit], suffix
            sweeps = [e for e in events if e["type"] == "tune_sweep"]
            assert bool(sweeps) is (not hit), (
                f"{suffix}: warm DB must pay ZERO re-sweeps, cold DB "
                "must sweep")
            assert any(e["type"] == "tune_probe" for e in events)
    if name == "bench_cache":
        # ISSUE 17: the hit-identity + all-hits + perturbation-miss
        # gates are enforced inside the bench at every shape; the
        # speedup number must exist (its >= 5x gate is disabled at
        # smoke shapes) and both cache traces must schema-validate
        # with the cache ledger populated
        assert out["all_hits"] is True
        assert out["hit_identical"] is True
        assert out["perturb_missed"] is True
        assert out["cache_speedup"] > 0
        assert out["speedup_ok"] is None  # gate disabled for smoke
        assert out["cache_bytes_served"] > 0
        assert out["router"] is not None
        assert out["router"]["router_hits_bypass_hosts"] is True
        assert out["router"]["tim_identical"] is True
        import io

        from pulseportraiture_tpu import telemetry

        for suffix, run in ((".cache", "ppserve"),
                            (".rcache", "pproute")):
            trace = str(tmp_path / "trace.jsonl") + suffix
            assert os.path.exists(trace), f"no {suffix} trace"
            manifest, events = telemetry.validate_trace(trace)
            assert manifest["run"] == run
            etypes = {e["type"] for e in events}
            for needed in ("cache_hit", "cache_miss", "cache_store"):
                assert needed in etypes, (suffix, needed)
            summary = telemetry.report(trace, file=io.StringIO())
            assert summary["n_cache_hit"] >= 6  # == PPT_NREQ
            assert summary["cache_hit_rate"] > 0
            assert summary["cache_bytes_served"] > 0
    if name == "bench_ingest":
        # ISSUE 18: every e2e gate is enforced inside the bench
        # (SystemExit on violation) — re-checked structurally here so
        # a silently skipped arm fails CI, and both pipeline traces
        # must schema-validate with the ingest/alert ledger
        assert out["tim_identical"] is True
        assert out["incremental_parity_ok"] is True
        assert out["incremental_max_rel"] <= 1e-10
        assert out["incremental_resolves"] >= 1
        assert out["n_alerts"] == 2
        assert out["glitch_mjd_err_d"] <= 1.0
        assert out["dm_step_mjd_err_d"] <= 1.0
        assert out["clean_alerts"] == 0
        assert out["detection_rate"] == 1.0
        assert out["fp_rate"] == 0.0
        assert out["admit_to_toa_p99_s"] >= \
            out["admit_to_toa_p50_s"] > 0
        assert out["p99_ok"] is None  # latency gate off for smoke
        import io as _io

        from pulseportraiture_tpu import telemetry

        for suffix, n_alert in ((".ingest", 2), (".clean", 0)):
            trace = str(tmp_path / "trace.jsonl") + suffix
            assert os.path.exists(trace), f"no {suffix} trace"
            _manifest, events = telemetry.validate_trace(trace)
            etypes = {e["type"] for e in events}
            for needed in ("ingest_admit", "request_done",
                           "batch_coalesce"):
                assert needed in etypes, (suffix, needed)
            summary = telemetry.report(trace, file=_io.StringIO())
            assert summary["n_ingest_admit"] == 6
            assert summary["n_alert"] == n_alert
            assert summary["incremental_resolves"] >= 1
    if name == "bench_obs":
        # ISSUE 20: observability must be free where it counts — the
        # byte gate and the merge gate are enforced inside the bench
        # (assert on violation); re-checked structurally here so a
        # silently skipped arm fails CI, and the on-arm's router +
        # host traces must schema-validate with the trace-id'd route
        # ledger stitching back together
        assert out["tim_identical"] is True
        assert out["merge_ok"] is True
        assert out["merge_frac"] == 1.0
        assert out["n_traces_merged"] == 3  # 1 router + 2 hosts
        assert out["overhead_ok"] is None  # gate disabled for smoke
        assert out["off_requests_per_sec"] > 0
        fv = out["fleet_view"]
        assert fv is not None
        assert fv["fleet_p99_s"] > 0 and fv["route_p99_s"] > 0
        assert set(fv["slo"]) == {"interactive", "bulk"}
        for s in fv["slo"].values():
            assert s["attainment"] is not None
        from pulseportraiture_tpu import telemetry
        from pulseportraiture_tpu.obs.merge import merge_traces

        traces = [str(tmp_path / "trace.jsonl") + ".obsr"] + [
            str(tmp_path / "trace.jsonl") + f".obs{h}"
            for h in range(2)]
        for trace in traces:
            assert os.path.exists(trace), trace
            telemetry.validate_trace(trace)
        _manifest, events = telemetry.validate_trace(traces[0])
        subs = [e for e in events if e["type"] == "route_submit"]
        assert subs and all(e.get("trace_id") for e in subs)
        merged = merge_traces(traces)
        routed = [r for r in merged["requests"].values()
                  if (r["req"] or "").startswith("on")]
        assert len(routed) == 2  # == PPT_NREQ
    if name == "bench_gauss":
        # ISSUE 9: both A/B arms must report, the in-memory oracle
        # digit gate must HOLD even at tiny shapes (engine drift fails
        # here, in CI), and the one-iteration LM attribution must
        # carry all four stages for BOTH Jacobian lanes (ISSUE 14; the
        # >= 3x, >= 1.5x and >= 0.9 gates belong to real bench runs at
        # the config-6 shape, not 2-pulsar smoke)
        assert out["digit_ok"] is True
        assert out["gmodel_max_delta"] <= out["digit_gate"]
        assert out["production_wall_s"] > 0
        assert out["batched_wall_s"] > 0
        assert out["ab_speedup_vs_serial"] > 0
        assert out["ab_speedup_vs_oracle_warm"] > 0
        assert out["gmodel_max_delta_vs_production"] <= 1e-6
        assert out["n_production_select_mismatch"] == 0
        for lane in ("ad", "analytic"):
            for stage in ("resid", "jacobian", "solve", "select"):
                assert f"{lane}_stage_{stage}_ms" in out, (lane, stage)
            assert out[f"{lane}_attributed_frac"] > 0
        assert out["dominant_stage_ad"]
        assert out["dominant_stage_analytic"]
        # ISSUE 14 digit gates, enforced in CI at tiny shapes: the
        # analytic-vs-jacfwd Jacobian on the real bucket problem, and
        # zero component-count selection flips between the lanes
        assert out["jac_digit_ok"] is True
        assert out["jac_rel_delta"] <= 1e-10
        assert out["jac_selection_flips_ok"] is True
        assert out["n_jac_selection_flips"] == 0
        assert out["iter_speedup_analytic_vs_ad"] > 0
        assert out["ab_speedup_analytic_vs_ad"] > 0
    if name == "bench_gls":
        # ISSUE 11: the serial arm pays one dispatch per pulsar, the
        # batched arm one per pow2 bucket — the reduction is the
        # headline; the digit gate must HOLD at tiny shapes (solver
        # drift fails here, in CI) and the trace must validate with
        # the timing-section summary keys
        assert out["digit_gate_ok"] is True
        assert out["digit_max"] <= 1e-10
        assert out["digit_max_vs_host"] <= 1e-8
        assert out["serial_dispatches"] == out["pulsars"] == 4
        assert out["batched_dispatches"] < out["serial_dispatches"]
        assert out["value"] > 1
        assert out["trace_validated"] is True
        from pulseportraiture_tpu import telemetry

        trace = str(tmp_path / "trace.jsonl")
        assert os.path.exists(trace), "bench_gls emitted no trace"
        manifest, events = telemetry.validate_trace(trace)
        etypes = {e["type"] for e in events}
        assert "timing_fit" in etypes and "fleet_end" in etypes
        fits = [e for e in events if e["type"] == "timing_fit"]
        assert all(e["batched"] for e in fits)
        assert sum(e["rows"] for e in fits) == 4
        ends = [e for e in events if e["type"] == "fleet_end"]
        assert ends[-1]["n_pulsars"] == 4
    if name == "bench_campaign":
        # ISSUE 6: the reworked link-bound bench must report both
        # pipeline arms with byte-identical .tim output and emit
        # schema-valid h2d events (validated inside the bench via
        # telemetry.report; re-checked structurally here)
        assert out["tim_identical"] is True
        assert set(out["pipeline"]) == {"1", "2"}
        for arm in out["pipeline"].values():
            assert arm["toas_per_sec"] > 0
            assert arm["h2d_bytes"] > 0 and arm["h2d_s"] >= 0
            # PPT_TELEMETRY was set: the pptrace link numbers rode in
            assert "link_stall_frac" in arm
        assert out["pipeline_speedup"] > 0
        from pulseportraiture_tpu import telemetry

        for depth in ("1", "2"):
            trace = str(tmp_path / "trace.jsonl") + f".d{depth}"
            assert os.path.exists(trace), f"no depth-{depth} trace"
            manifest, events = telemetry.validate_trace(trace)
            h2d_done = [e for e in events if e["type"] == "h2d_done"]
            assert h2d_done, "bench_campaign emitted no h2d events"
            for ev in h2d_done:
                assert ev["bytes"] > 0 and ev["h2d_s"] >= 0
                assert isinstance(ev["overlap"], bool)
                # ISSUE 15: the compression-accounting fields are
                # schema-required on every h2d_done now
                assert ev["bytes_logical"] >= ev["bytes"]
                assert ev["codec_s"] >= 0
        # ISSUE 15: the sub-byte arm's >= 8x byte gate and digit gate
        # are enforced INSIDE the bench at every shape; re-checked
        # structurally here so a silently skipped arm fails CI
        sub = out["subbyte"]
        assert sub["tim_identical"] is True
        assert sub["byte_ratio"] >= 8.0
        assert sub["packed_bytes"] < sub["fallback_bytes"]
        # the compression arm: 'on' shrinks shipped bytes at identical
        # .tim; 'auto' never engages on the bare-CPU smoke link
        cmp_arm = out["compression"]
        assert cmp_arm["tim_identical"] is True
        assert cmp_arm["compress_ratio_on"] > 1.0
        assert cmp_arm["True"]["h2d_bytes"] < \
            cmp_arm["False"]["h2d_bytes"]
        assert cmp_arm["auto_engaged"] is False


@pytest.mark.slow
def test_bench_root_fused_arm(monkeypatch, capsys):
    """ISSUE 14: the headline fit bench (repo-root bench.py) carries a
    fused-vs-unfused A/B whose bitwise gate is ENFORCED in-bench
    (SystemExit on drift) — run it at a tiny windowed shape so fusion
    drift fails in CI.  config.fit_fused flips inside the bench; the
    knob is restored by the bench itself."""
    import importlib.util

    monkeypatch.setenv("PPT_NB", "8")
    monkeypatch.setenv("PPT_NCHAN", "8")
    monkeypatch.setenv("PPT_NBIN", "1024")
    saved = {k: getattr(config, k) for k in _CONFIG_KEYS}
    spec = importlib.util.spec_from_file_location(
        "bench_root", os.path.join(BENCH_DIR, "..", "bench.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    try:
        mod.main()
    finally:
        for k, v in saved.items():
            setattr(config, k, v)
    lines = [ln for ln in capsys.readouterr().out.splitlines()
             if ln.strip().startswith("{")]
    assert lines, "bench.py printed no JSON line"
    out = json.loads(lines[-1])
    # the window must be active or the fused arm never ran (the A/B is
    # windowed-only by design)
    assert out["harmonic_window"] is not None
    assert out["fused_identical"] is True
    assert out["fused_vs_unfused"] > 0
    assert out["accuracy_gate_1e-4"] is True


def test_bench_root_pallas_arm(monkeypatch, capsys):
    """ISSUE 16: with PPT_FIT_PALLAS=on the headline bench adds the
    Pallas-kernel arm, interpret mode on CPU, with the same ENFORCED
    bitwise gate (SystemExit on drift) — the fast CI witness that a
    kernel edit cannot land with phi drift.  The forced window
    (PPT_HARMONIC_WINDOW) keeps the shape tiny: the content-derived
    window refuses 256-bin templates."""
    import importlib.util

    monkeypatch.setenv("PPT_NB", "8")
    monkeypatch.setenv("PPT_NCHAN", "8")
    monkeypatch.setenv("PPT_NBIN", "256")
    monkeypatch.setenv("PPT_HARMONIC_WINDOW", "128")
    monkeypatch.setenv("PPT_FIT_PALLAS", "on")
    saved = {k: getattr(config, k) for k in _CONFIG_KEYS}
    spec = importlib.util.spec_from_file_location(
        "bench_root_pallas", os.path.join(BENCH_DIR, "..", "bench.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    try:
        mod.main()
    finally:
        for k, v in saved.items():
            setattr(config, k, v)
    lines = [ln for ln in capsys.readouterr().out.splitlines()
             if ln.strip().startswith("{")]
    assert lines, "bench.py printed no JSON line"
    out = json.loads(lines[-1])
    assert out["harmonic_window"] == 128
    assert out["fused_identical"] is True
    assert out["pallas_identical"] is True
    assert out["pallas_interpret"] is True  # CPU = interpret mode
    assert out["pallas_toas_per_sec"] > 0
    assert out["accuracy_gate_1e-4"] is True
