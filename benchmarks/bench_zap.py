"""Inline-device vs host-offline RFI excision A/B (ISSUE 12
acceptance gate).

Arms over one synthetic RFI-contaminated campaign (narrowband tones +
a broadband burst, known ground truth):

  offline  — the pre-ISSUE-12 workflow: per-archive ppzap median
             proposals with HOST statistics (the reference loop — one
             (median, std) pull per iteration per subint), then the
             fit with the lists applied as lossless weight zaps
             (``zap_channels=``);
  inline   — ``zap_inline=True``: the cut FUSED into the raw bucket's
             device program (the whole iteration inside the compiled
             while_loop on the device-resident noise levels), masks
             zeroed before the fit consumes them;
  device   — the standalone batched device proposal
             (one dispatch per archive), timed against the host
             proposal loop: the zap-wall A/B.

Gates, enforced EVERY run (tiny CI smoke shapes included):

  zap_digit_ok   — host and device flagged-channel lists identical on
                   the whole corpus (the excision digit gate);
  truth_ok       — the injector's ground-truth channels are all
                   recovered;
  tim_identical  — inline .tim == offline-oracle .tim, byte-for-byte;
  clean_ok       — on the CLEAN control corpus the cut flags nothing
                   and .tim with the quality machinery on equals the
                   plain run byte-for-byte.

Under PPT_TELEMETRY the inline arm's trace is schema-validated and
must carry the zap_apply ledger.  Knobs: PPT_NARCH (default 8),
PPT_NSUB (4), PPT_NCHAN (32), PPT_NBIN (256).  Prints ONE JSON line.
"""

import json
import os
import sys
import time

sys.path.insert(0, __file__.rsplit("/", 2)[0])


def main():
    import pulseportraiture_tpu  # noqa: F401
    from pulseportraiture_tpu import config
    config.env_overrides()

    import numpy as np

    from pulseportraiture_tpu import telemetry
    from pulseportraiture_tpu.io.gmodel import write_gmodel
    from pulseportraiture_tpu.io.psrfits import load_data
    from pulseportraiture_tpu.pipeline.stream import stream_wideband_TOAs
    from pulseportraiture_tpu.pipeline.zap import get_zap_channels
    from pulseportraiture_tpu.synth import (default_test_model,
                                            inject_rfi, make_fake_pulsar)

    NARCH = int(os.environ.get("PPT_NARCH", 8))
    NSUB = int(os.environ.get("PPT_NSUB", 4))
    NCHAN = int(os.environ.get("PPT_NCHAN", 32))
    NBIN = int(os.environ.get("PPT_NBIN", 256))
    # the inline arm owns the trace explicitly; without this, every
    # driver call in the bench would pick the PPT_TELEMETRY path up
    # from config and rotate the arm-under-test's trace away
    trace_path = config.telemetry_path
    config.telemetry_path = None

    import tempfile

    root = tempfile.mkdtemp(prefix="ppt_zap_bench_")
    model = default_test_model(1500.0)
    gmodel = os.path.join(root, "model.gmodel")
    write_gmodel(model, gmodel, quiet=True)
    par = {"PSR": "J1744-1134", "P0": 0.004074, "PEPOCH": 55000.0,
           "DM": 3.139}

    def corpus(tag, contaminated):
        files, truths = [], []
        for i in range(NARCH):
            path = os.path.join(root, f"{tag}{i}.fits")
            make_fake_pulsar(model, par, outfile=path, nsub=NSUB,
                             nchan=NCHAN, nbin=NBIN, nu0=1500.0,
                             bw=800.0, tsub=60.0, phase=0.003 * i,
                             dDM=1e-4 * (i % 3 - 1), noise_stds=0.05,
                             dedispersed=False, quiet=True, rng=500 + i)
            if contaminated:
                # <= 2 contaminated channels per cut round (masking
                # breakdown margin, see tests/test_quality.py)
                tones = [(3 + 5 * i) % NCHAN, (11 + 7 * i) % NCHAN]
                if tones[0] == tones[1]:
                    tones[1] = (tones[1] + 1) % NCHAN
                truths.append(inject_rfi(
                    path, tone_channels=tones, tone_white=8.0,
                    tone_structured=40.0,
                    bursts=[(i % NSUB, [(20 + i) % NCHAN], 20.0)],
                    rng=900 + i))
            files.append(path)
        return files, truths

    rfi_files, truths = corpus("rfi", True)
    clean_files, _ = corpus("clean", False)

    # ---- proposals: host loop vs one-dispatch device lane ------------
    loads = {f: load_data(f, dedisperse=False, dededisperse=True,
                          pscrunch=True, quiet=True)
             for f in rfi_files}
    t0 = time.perf_counter()
    host_lists = {f: get_zap_channels(d, device=False)
                  for f, d in loads.items()}
    host_zap_s = time.perf_counter() - t0
    # one throwaway call compiles the program; then time warm
    get_zap_channels(loads[rfi_files[0]], device=True)
    t0 = time.perf_counter()
    dev_lists = {f: get_zap_channels(d, device=True)
                 for f, d in loads.items()}
    dev_zap_s = time.perf_counter() - t0
    zap_digit_ok = host_lists == dev_lists

    truth_ok = True
    zap_map = dict(host_lists)  # rows indexed by true subint number
    for f, tr in zip(rfi_files, truths):
        for isub, expect in enumerate(tr.zap_truth):
            if not set(expect) <= set(zap_map[f][isub]):
                truth_ok = False

    # ---- fits: offline oracle vs fused inline ------------------------
    tim_off = os.path.join(root, "offline.tim")
    tim_inl = os.path.join(root, "inline.tim")
    t0 = time.perf_counter()
    stream_wideband_TOAs(rfi_files, gmodel, nsub_batch=max(NSUB, 8),
                         quiet=True, tim_out=tim_off,
                         zap_channels=zap_map)
    offline_fit_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    res = stream_wideband_TOAs(rfi_files, gmodel,
                               nsub_batch=max(NSUB, 8), quiet=True,
                               tim_out=tim_inl, zap_inline=True,
                               telemetry=trace_path)
    inline_fit_s = time.perf_counter() - t0
    tim_identical = (open(tim_off, "rb").read()
                     == open(tim_inl, "rb").read())

    # ---- clean control: the quality machinery must be a no-op --------
    clean_flags = 0
    for f in clean_files:
        d = load_data(f, dedisperse=False, dededisperse=True,
                      pscrunch=True, quiet=True)
        clean_flags += sum(len(z) for z in
                           get_zap_channels(d, device=False))
    tim_ca = os.path.join(root, "clean_plain.tim")
    tim_cb = os.path.join(root, "clean_inline.tim")
    stream_wideband_TOAs(clean_files, gmodel, nsub_batch=max(NSUB, 8),
                         quiet=True, tim_out=tim_ca)
    stream_wideband_TOAs(clean_files, gmodel, nsub_batch=max(NSUB, 8),
                         quiet=True, tim_out=tim_cb, zap_inline=True)
    clean_ok = (clean_flags == 0
                and open(tim_ca, "rb").read()
                == open(tim_cb, "rb").read())

    trace_ok = None
    if trace_path:
        manifest, events = telemetry.validate_trace(trace_path)
        apps = [e for e in events if e["type"] == "zap_apply"]
        assert len(apps) == len(rfi_files), (
            f"expected one zap_apply per archive, got {len(apps)}")
        assert sum(e["n_channels"] for e in apps) == sum(
            sum(len(z) for z in full) for full in zap_map.values())
        trace_ok = True

    assert zap_digit_ok, "host/device flagged-channel lists diverged"
    assert truth_ok, "injected ground-truth channels not recovered"
    assert tim_identical, "inline .tim != offline-oracle .tim"
    assert clean_ok, "quality machinery perturbed a clean corpus"

    n_cut = sum(sum(len(z) for z in full) for full in zap_map.values())
    out = {
        "metric": "zap_host_vs_device_wall",
        "value": host_zap_s / max(dev_zap_s, 1e-9),
        "unit": "x (host proposal wall / one-dispatch device wall)",
        "narch": NARCH, "nsub": NSUB, "nchan": NCHAN, "nbin": NBIN,
        "host_zap_s": round(host_zap_s, 4),
        "device_zap_s": round(dev_zap_s, 4),
        "offline_fit_s": round(offline_fit_s, 3),
        "inline_fit_s": round(inline_fit_s, 3),
        "inline_toas_per_s": round(
            len(res.TOA_list) / max(inline_fit_s, 1e-9), 2),
        "channels_cut": int(n_cut),
        "zap_digit_ok": bool(zap_digit_ok),
        "truth_ok": bool(truth_ok),
        "tim_identical": bool(tim_identical),
        "clean_ok": bool(clean_ok),
        "trace_ok": trace_ok,
        "backend": __import__("jax").default_backend(),
    }
    print(json.dumps(out))
    return out


if __name__ == "__main__":
    main()
