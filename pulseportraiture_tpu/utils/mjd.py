"""High-precision MJD arithmetic.

TOA epochs need ~1e-13 day (~10 ns) precision — beyond a single
float64.  The reference leans on PSRCHIVE's C++ MJD class
(pptoas.py:572-575); here we keep a host-side (int day, float64
fractional day) pair, which holds ~1e-17 day of precision in the
fraction.
"""

from dataclasses import dataclass

SECPERDAY = 86400.0


@dataclass(frozen=True)
class MJD:
    """An epoch as (integer MJD, fractional day in [0, 1))."""

    day: int
    frac: float

    def __post_init__(self):
        # normalize so 0 <= frac < 1 exactly once at construction
        d = int(self.frac // 1.0)
        if d != 0:
            object.__setattr__(self, "day", self.day + d)
            object.__setattr__(self, "frac", self.frac - d)

    @classmethod
    def from_float(cls, mjd):
        d = int(mjd // 1.0)
        return cls(d, float(mjd) - d)

    def add_days(self, days):
        d = int(days // 1.0)
        return MJD(self.day + d, self.frac + (days - d))

    def add_seconds(self, sec):
        return self.add_days(sec / SECPERDAY)

    def __add__(self, days):
        return self.add_days(days)

    def __sub__(self, other):
        """Difference in days (float) against another MJD."""
        if isinstance(other, MJD):
            return (self.day - other.day) + (self.frac - other.frac)
        return self.add_days(-other)

    def to_float(self):
        return self.day + self.frac

    def tim_string(self, ndecimals=15):
        """'{day}.{frac}' with the fraction rendered to ndecimals —
        full precision for .tim files (reference pplib.py:3551-3585
        writes 13 decimals; we default to 15)."""
        frac_str = f"{self.frac:.{ndecimals}f}"
        if frac_str.startswith("1"):  # rounding carried over
            return MJD(self.day + 1, 0.0).tim_string(ndecimals)
        return f"{self.day}{frac_str[1:]}"

    def __repr__(self):
        return f"MJD({self.tim_string()})"
