"""DataBunch — the universal record type.

A dict with attribute access, mirroring the reference's DataBunch
(pplib.py:142-152) so users migrating from PulsePortraiture find the
same ergonomics (`data.freqs` == `data['freqs']`).  Values are host
numpy arrays / scalars; device code receives explicit array arguments,
never a bunch.
"""


class DataBunch(dict):
    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        self.__dict__ = self

    def __repr__(self):
        keys = ", ".join(sorted(self.keys()))
        return f"DataBunch({keys})"
