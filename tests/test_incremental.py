"""Incremental GLS timing (ISSUE 18, layer 2): the rank-update lane
must match the batch solver to <= 1e-10 relative at EVERY update,
resolve on its configured cadence, and refuse loudly when the
accumulated normal equations drift from the batch oracle."""

import numpy as np
import pytest

from pulseportraiture_tpu.synth.fake import fake_timing_campaign
from pulseportraiture_tpu.timing import (GLSDriftError, IncrementalGLS,
                                         wideband_gls_fit)

PAR = {"PSR": "FAKE", "F0": "218.8", "PEPOCH": "55500", "DM": "15.9"}
BPAR = dict(PAR, PB="1.53", A1="1.89", TASC="55499.5",
            EPS1="2.1e-7", EPS2="-1.4e-7")


def _campaign(par, rng=0, **kw):
    kw.setdefault("n_epochs", 8)
    kw.setdefault("toas_per_epoch", 3)
    kw.setdefault("span_days", 80.0)
    kw.setdefault("dmx", 2e-4)
    return fake_timing_campaign(par, rng=rng, **kw)


def _rel(a, b):
    return np.max(np.abs(np.asarray(a) - np.asarray(b))
                  / np.maximum(1.0, np.abs(b)))


# the binary-orbit sweep is ~14 s (every-prefix batch refits with four
# Keplerian columns); the non-binary sweep keeps the every-update
# parity gate tier-1 and benchmarks/bench_ingest.py replays it e2e
@pytest.mark.parametrize(
    "par,fit_binary",
    [(PAR, False),
     pytest.param(BPAR, True, marks=pytest.mark.slow)])
def test_incremental_matches_batch_at_every_update(par, fit_binary):
    """The acceptance core: after every single update the incremental
    params/dmx match a from-scratch batch fit over the same prefix to
    <= 1e-10 relative.  The first handful of binary-orbit prefixes are
    conditioning-limited (four Keplerian columns riding a few TOAs:
    BOTH solvers' pseudo-inverses wobble there), so the strict gate
    starts once the system is comfortably overdetermined and the early
    prefixes get a conditioning-scaled bound instead."""
    toas, _ = _campaign(par, rng=1)
    strict_from = 8 if fit_binary else 1
    inc = IncrementalGLS(par, fit_binary=fit_binary, resolve_every=0)
    for i, toa in enumerate(toas):
        res = inc.update(toa)
        if i < 1:
            assert res is None
            continue
        tol = 1e-10 if i >= strict_from else 1e-4
        batch = wideband_gls_fit(toas[:i + 1], par,
                                 fit_binary=fit_binary)
        for name, val in batch.params.items():
            assert abs(res.params[name] - val) \
                <= tol * max(1.0, abs(val)), (i, name)
        assert _rel(res.dmx, batch.dmx) <= tol, i
        assert _rel(res.time_resids_us, batch.time_resids_us) \
            <= max(tol, 1e-8), i
    assert inc.n_updates == len(toas) - 1


def test_incremental_out_of_order_arrival_rebuilds():
    """A TOA arriving out of MJD order renumbers the epochs: the lane
    must detect the structural change, rebuild, and still match the
    batch fit exactly."""
    toas, _ = _campaign(PAR, rng=2)
    rng = np.random.default_rng(5)
    shuffled = list(toas)
    rng.shuffle(shuffled)
    inc = IncrementalGLS(PAR, fit_binary=False, resolve_every=0)
    res = None
    for toa in shuffled:
        res = inc.update(toa)
    batch = wideband_gls_fit(shuffled, PAR, fit_binary=False)
    for name, val in batch.params.items():
        assert abs(res.params[name] - val) \
            <= 1e-10 * max(1.0, abs(val)), name
    assert _rel(res.dmx, batch.dmx) <= 1e-10


def test_incremental_resolve_cadence_and_counter():
    """resolve_every=N: exactly floor(n_updates/N) full resolves, each
    cross-checking the running solution against the batch oracle."""
    toas, _ = _campaign(PAR, rng=3)
    inc = IncrementalGLS(PAR, fit_binary=False, resolve_every=5)
    for toa in toas:
        inc.update(toa)
    assert inc.n_resolves == inc.n_updates // 5
    # resolve_every=0 disables the cadence entirely
    inc0 = IncrementalGLS(PAR, fit_binary=False, resolve_every=0)
    for toa in toas:
        inc0.update(toa)
    assert inc0.n_resolves == 0


def test_incremental_drift_gate_refuses_loudly():
    """Corrupt the accumulated normal equations between updates: the
    next periodic resolve must raise GLSDriftError naming the drift —
    a silently-wrong warm solution is the one unacceptable outcome."""
    toas, _ = _campaign(PAR, rng=4)
    toas = sorted(toas, key=lambda t: t.mjd_int + t.mjd_frac)
    inc = IncrementalGLS(PAR, fit_binary=False, resolve_every=4)
    with pytest.raises(GLSDriftError, match="drifted"):
        for i, toa in enumerate(toas):
            if i == 6:
                inc._b = inc._b * 1.5  # simulated bitrot / logic bug
            inc.update(toa)


def test_incremental_drops_no_dm_toas():
    """TOAs without wideband DM measurements cannot enter the DMDATA
    system; the lane counts them like the batch fit does."""
    import dataclasses

    toas, _ = _campaign(PAR, rng=6)
    broken = dataclasses.replace(toas[3], dm=None, dm_err=None)
    inc = IncrementalGLS(PAR, fit_binary=False, resolve_every=0)
    for toa in toas[:3] + [broken] + toas[4:]:
        inc.update(toa)
    assert inc.result.n_dropped_no_dm == 1
    batch = wideband_gls_fit([t for t in toas if t is not toas[3]],
                             PAR, fit_binary=False)
    assert _rel(inc.result.dmx, batch.dmx) <= 1e-10


def test_incremental_rejects_unusable_par():
    with pytest.raises(ValueError, match="PEPOCH"):
        IncrementalGLS({"PSR": "X", "F0": "100"})
    with pytest.raises(ValueError, match="F0"):
        IncrementalGLS({"PSR": "X", "PEPOCH": "55000"})
