from .phase_shift import fit_phase_shift, fit_phase_shift_batch
from .powlaw import fit_powlaw, fit_DM_to_freq_resids, powlaw, powlaw_freqs
from .lm import levenberg_marquardt, LMResult
from .gauss import fit_gaussian_profile, fit_gaussian_portrait
from .portrait import (
    FitFlags,
    FitResult,
    fit_portrait,
    fit_portrait_batch,
    fit_portrait_batch_fast,
    chi2_prime,
)

__all__ = [
    "fit_phase_shift",
    "fit_phase_shift_batch",
    "FitFlags",
    "FitResult",
    "fit_portrait",
    "fit_portrait_batch",
    "fit_portrait_batch_fast",
    "chi2_prime",
    "fit_powlaw",
    "fit_DM_to_freq_resids",
    "powlaw",
    "powlaw_freqs",
    "levenberg_marquardt",
    "LMResult",
    "fit_gaussian_profile",
    "fit_gaussian_portrait",
]
