"""Model-file formats + TOA writer tests.

Oracles: write -> read round-trips preserve parameters exactly
(text precision for gmodel); the reference's own example.gmodel
grammar (comments, trailing flag comments) parses; tim lines contain
the -pp_dm/-pp_dme flags with the documented formatting.
"""

import numpy as np
import pytest

from pulseportraiture_tpu.io.gmodel import (
    gen_gmodel_portrait,
    model_from_flat,
    model_to_flat,
    read_gmodel,
    write_gmodel,
)
from pulseportraiture_tpu.io.splmodel import (
    SplineModel,
    read_spline_model,
    spline_model_coords,
    write_spline_model,
)
from pulseportraiture_tpu.io.tim import (
    TOA,
    filter_TOAs,
    toa_string,
    write_TOAs,
)
from pulseportraiture_tpu.utils.mjd import MJD


def _toy_model():
    return model_from_flat(
        "TEST_MODEL", "000", 1400.0,
        [0.001, 0.0,
         0.25, -0.005, 0.03, -2.0, 5.0, -1.5,
         0.30, 0.002, 0.015, 1.6, 9.0, -2.0],
        [1, 0, 1, 1, 1, 1, 1, 1, 1, 1, 1, 1, 1, 1],
        alpha=-4.0, fit_alpha=0)


def test_gmodel_roundtrip(tmp_path):
    m = _toy_model()
    path = tmp_path / "m.gmodel"
    write_gmodel(m, path, quiet=True)
    back = read_gmodel(path, quiet=True)
    assert back.name == "TEST_MODEL"
    assert back.code == "000"
    assert back.nu_ref == 1400.0
    assert back.ngauss == 2
    p0, f0 = model_to_flat(m)
    p1, f1 = model_to_flat(back)
    np.testing.assert_allclose(p1, p0, atol=1e-8)
    np.testing.assert_array_equal(f1, f0)
    assert back.fit_flags["alpha"] == 0


def test_gmodel_reference_grammar(tmp_path):
    """A file in the exact documented grammar (with comment lines and
    a trailing '#FIT flag' comment on ALPHA) parses."""
    text = """#A comment
MODEL   PSR_TEST
CODE    010

FREQ    1300.00000
DC      0.00889801 1
TAU     0.00000000 1
ALPHA  -4.000      0  #FIT flag

#COMPNN     LOC   FIT? ...
COMP01  0.21925557 1  -0.00518501 1   0.04823579 1  -2.08031160 1    5.13274758 1   -1.65717015 1
COMP02  0.23409622 1  -0.00271530 1   0.01573809 1   1.61520300 1    9.46117549 1   -2.07617616 1
"""
    path = tmp_path / "ref.gmodel"
    path.write_text(text)
    m = read_gmodel(path, quiet=True)
    assert m.ngauss == 2
    assert m.code == "010"
    assert m.alpha == -4.0
    assert m.locs[0] == pytest.approx(0.21925557)
    assert m.mamps[1] == pytest.approx(-2.07617616)
    port = gen_gmodel_portrait(m, np.arange(128), [1250.0, 1350.0])
    assert port.shape == (2, 128)
    assert np.isfinite(port).all()


def test_gmodel_portrait_scattering_needs_P(tmp_path):
    m = _toy_model()
    m.tau = 1e-4
    with pytest.raises(ValueError):
        gen_gmodel_portrait(m, np.arange(64), [1400.0])
    port = gen_gmodel_portrait(m, np.arange(64), [1400.0], P=0.005)
    assert np.isfinite(port).all()


def test_spline_model_roundtrip(tmp_path):
    rng = np.random.default_rng(0)
    nbin, ncomp, ncoef = 64, 2, 7
    t = np.concatenate([[1200.0] * 4, [1350.0, 1500.0, 1650.0],
                        [1800.0] * 4])
    model = SplineModel(
        modelname="spl_test", source="J0000+0000", datafile="avg.fits",
        mean_prof=rng.normal(size=nbin),
        eigvec=rng.normal(size=(nbin, ncomp)),
        tck=(t, rng.normal(size=(ncomp, ncoef)), 3))
    for name in ("m.spl", "m.ppspl.npz"):
        path = tmp_path / name
        write_spline_model(model, path, quiet=True)
        back = read_spline_model(path, quiet=True)
        assert back.modelname == "spl_test"
        np.testing.assert_allclose(back.mean_prof, model.mean_prof)
        np.testing.assert_allclose(back.eigvec, model.eigvec)
        np.testing.assert_allclose(back.tck[0], model.tck[0])
        np.testing.assert_allclose(back.tck[1], model.tck[1])
        assert back.tck[2] == 3
        # evaluation parity between forms
        freqs = np.linspace(1250.0, 1750.0, 5)
        np.testing.assert_allclose(back.portrait(freqs),
                                   model.portrait(freqs), atol=1e-10)
    coords = spline_model_coords(model, [1400.0, 1500.0])
    assert coords.shape == (2, ncomp)


def test_spline_eval_matches_scipy():
    import scipy.interpolate as si

    rng = np.random.default_rng(1)
    x = np.linspace(1200.0, 1800.0, 40)
    y = np.vstack([np.sin(x / 100.0), np.cos(x / 150.0)])
    (tck, u), _ = si.splprep([y[0], y[1]], u=x, s=1.0), None
    model = SplineModel("m", "s", "d", np.zeros(8),
                        np.zeros((8, 2)), tck)
    got = spline_model_coords(model, x)
    want = np.array(si.splev(x, tck)).T
    np.testing.assert_allclose(got, want, atol=1e-8)


def _toy_toas():
    return [
        TOA("a.fits", 1450.0, MJD(55000, 0.25), 1.5, "GBT", "1",
            DM=10.0000005, DM_error=2e-4,
            flags={"be": "GUPPI", "snr": 50.0, "subint": 0,
                   "phs": 0.123456789, "flux": 1.23456,
                   "phi_dm_cov": 1.3e-9}),
        TOA("b.fits", np.inf, MJD(55001, 0.5), 2.5, "GBT", "1",
            flags={"snr": 5.0}),
    ]


def test_toa_string_format():
    toas = _toy_toas()
    s = toa_string(toas[0])
    parts = s.split()
    assert parts[0] == "a.fits"
    assert parts[1] == "1450.00000000"
    assert parts[2].startswith("55000.250000")
    assert "-pp_dm 10.0000005" in s
    assert "-pp_dme 0.0002000" in s
    assert "-be GUPPI" in s
    assert "-subint 0" in s
    assert "-phs 0.12345679" in s
    assert "-flux 1.23456" in s
    assert "-phi_dm_cov 1.3e-09" in s
    # infinite frequency -> 0.0 MHz (TEMPO2 convention)
    s2 = toa_string(toas[1])
    assert s2.split()[1] == "0.00000000"


def test_write_and_filter_toas(tmp_path):
    toas = _toy_toas()
    out = tmp_path / "t.tim"
    write_TOAs(toas, outfile=str(out), SNR_cutoff=10.0)
    lines = out.read_text().strip().splitlines()
    assert len(lines) == 1  # snr=5 filtered out
    assert lines[0].startswith("a.fits")
    # append behavior
    write_TOAs(toas, outfile=str(out), SNR_cutoff=0.0)
    assert len(out.read_text().strip().splitlines()) == 3
    kept, culled = filter_TOAs(toas, "snr", 10.0, ">=",
                               return_culled=True)
    assert len(kept) == 1 and len(culled) == 1
    # unknown flag: pass_unflagged
    kept = filter_TOAs(toas, "nosuch", 0, pass_unflagged=True)
    assert len(kept) == 2
