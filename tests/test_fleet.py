"""Elastic fleet subsystem (ISSUE 13): the health state machine and
bounded probes, dynamic membership (add/remove + the watched fleet
file), exactly-once mid-fit failover off the durable-.tim property,
hedged requests, the no-shared-fs codec lane, per-tenant QoS lanes,
and refit-aware routing — each gated against the one-shot driver's
byte-identical .tim output."""

import io
import json
import os
import threading
import time

import numpy as np
import pytest

from pulseportraiture_tpu import config, telemetry
from pulseportraiture_tpu.io import write_gmodel
from pulseportraiture_tpu.pipeline import stream_wideband_TOAs
from pulseportraiture_tpu.serve import (DEAD, HEALTHY, JOINING,
                                        REJOINED, SUSPECT,
                                        AdmissionQueue, Fleet,
                                        InProcTransport, ServeRequest,
                                        SocketTransport, ToaRouter,
                                        ToaServer, TransportError,
                                        TransportServer,
                                        read_tim_result, tim_complete,
                                        write_tim_result)
from pulseportraiture_tpu.serve.codec import (decode_result,
                                              encode_result)
from pulseportraiture_tpu.synth import default_test_model, make_fake_pulsar
from pulseportraiture_tpu.utils.bunch import DataBunch
from pulseportraiture_tpu.utils.mjd import MJD

PAR = {"PSR": "J1744-1134", "RAJ": "17:44:29.4", "DECJ": "-11:34:54.6",
       "P0": 0.004074, "PEPOCH": 55000.0, "DM": 3.139}


@pytest.fixture(scope="module")
def campaign(tmp_path_factory):
    """4 archives, two bucket shapes (the test_router corpus)."""
    root = tmp_path_factory.mktemp("fleet")
    model = default_test_model(1500.0)
    gmodel = str(root / "model.gmodel")
    write_gmodel(model, gmodel, quiet=True)
    files = []
    for i in range(4):
        path = str(root / f"ep{i}.fits")
        make_fake_pulsar(model, PAR, outfile=path, nsub=2,
                         nchan=16 if i < 2 else 12, nbin=128,
                         nu0=1500.0, bw=400.0, tsub=60.0,
                         phase=0.01 * i, dDM=1e-4,
                         start_MJD=MJD(55100 + i, 0.1), noise_stds=0.08,
                         dedispersed=False, quiet=True, rng=200 + i)
        files.append(path)
    ref = str(root / "ref01.tim")
    stream_wideband_TOAs(files[:2], gmodel, nsub_batch=8, tim_out=ref,
                         quiet=True)
    return files, gmodel, open(ref, "rb").read()


# dead-host emulation: the shared fault-injection wrapper
# (serve/transport.KillableTransport) — one definition for tests AND
# bench_router's kill arm, so both exercise the same failure semantics
from pulseportraiture_tpu.serve.transport import (  # noqa: E402
    KillableTransport as _Killable)


class _FakeTransport:
    """stat-only stub for state-machine units; scripted to succeed or
    raise."""

    def __init__(self, label):
        self.label = label
        self.fail = False
        self.n_stats = 0

    def stat(self):
        self.n_stats += 1
        if self.fail:
            raise TransportError(f"{self.label} down")
        return {"pending_archives": 0, "queue_len": 0, "n_live": 0}

    def close(self):
        pass


# ---------------------------------------------------------------------------
# health state machine + probes
# ---------------------------------------------------------------------------

def test_fleet_state_machine_walks_every_edge(tmp_path):
    """JOINING -> HEALTHY -> SUSPECT -> DEAD -> REJOINED -> HEALTHY,
    with a loud fleet_transition event per edge and the DEAD callback
    firing exactly once per death."""
    trace = str(tmp_path / "fsm.jsonl")
    tracer = telemetry.Tracer(trace, run="fsm")
    deaths = []
    fleet = Fleet(tracer=tracer, probe_ms=200,
                  on_dead=deaths.append, quiet=True)
    from pulseportraiture_tpu.serve.fleet import PLACEABLE_STATES

    t = _FakeTransport("h0")
    m = fleet.add(t)
    assert m.state == JOINING
    assert JOINING not in PLACEABLE_STATES
    assert fleet.probe_all() == {m: 0}  # the probe promoted it...
    assert m.state == HEALTHY           # ...inside the bounded pass
    t.fail = True
    fleet.record_error(m, "submit: boom")
    assert m.state == SUSPECT
    assert SUSPECT in PLACEABLE_STATES  # degraded but placeable
    fleet.probe_all()
    assert m.state == DEAD              # second failure -> DEAD
    assert deaths == [m]
    assert fleet.probe_all() == {}
    t.fail = False
    time.sleep(1.1)                # DEAD reprobe throttle
    fleet.probe_all()
    assert m.state == REJOINED     # one success steps DEAD forward
    fleet.probe_all()
    assert m.state == HEALTHY      # the next confirms the rejoin
    assert deaths == [m]
    fleet.close()
    tracer.close()
    _, events = telemetry.validate_trace(trace)
    edges = [(e["from_state"], e["to_state"]) for e in events
             if e["type"] == "fleet_transition"]
    assert (None, "JOINING") == edges[0]
    for edge in [("JOINING", "HEALTHY"), ("HEALTHY", "SUSPECT"),
                 ("SUSPECT", "DEAD"), ("DEAD", "REJOINED"),
                 ("REJOINED", "HEALTHY")]:
        assert edge in edges, (edge, edges)


def test_probe_timeout_bounds_placement_and_feeds_suspect(campaign):
    """The probe-deadline fix: a host whose stat() hangs must not
    delay a placement pass past config.router_probe_ms — the cached
    load is used and the hung host transitions to SUSPECT instead of
    blocking submit."""
    files, gmodel, _ = campaign

    class _Hung:
        def __init__(self, inner):
            self.inner = inner
            self.label = inner.label
            self.hang = threading.Event()

        def stat(self):
            if self.hang.is_set():
                self.hang.wait(5.0)  # far beyond the probe deadline
            return self.inner.stat()

        def __getattr__(self, name):
            return getattr(self.inner, name)

    with ToaServer(nsub_batch=8, max_wait_ms=30, quiet=True) as h0, \
            ToaServer(nsub_batch=8, max_wait_ms=30, quiet=True) as h1:
        hung = _Hung(InProcTransport(h0, label="p0"))
        router = ToaRouter([hung, InProcTransport(h1, label="p1")],
                           probe_ms=100)
        router.get_TOAs(files[:1], gmodel, timeout=300, name="warm")
        hung.hang.set()
        t0 = time.monotonic()
        res = router.get_TOAs(files[1:2], gmodel, timeout=300,
                              name="bounded")
        placement_wall = time.monotonic() - t0
        states = {k: v["state"] for k, v in router.stats().items()}
        hung.hang.clear()
        router.close()
    assert len(res.TOA_list) == 2
    # the fit itself costs ~1 s; the probe must not add its 5 s hang
    assert placement_wall < 4.0, placement_wall
    assert states["p0"] in (SUSPECT, HEALTHY)  # HEALTHY if the
    # follow-up submit landed on p0 (a successful submit is itself
    # health evidence); either way the hang never blocked placement


def test_membership_add_remove_and_fleet_file(campaign, tmp_path):
    """Dynamic membership: hosts join/leave at runtime, placement
    follows, and the watched fleet file reconciles membership
    (unreachable entries warn and retry instead of failing the
    router)."""
    files, gmodel, _ = campaign
    with ToaServer(nsub_batch=8, max_wait_ms=30, quiet=True) as h0, \
            ToaServer(nsub_batch=8, max_wait_ms=30, quiet=True) as h1:
        router = ToaRouter([InProcTransport(h0, label="m0")])
        assert router.host_labels() == ["m0"]
        router.add_host(InProcTransport(h1, label="m1"))
        with pytest.raises(ValueError, match="duplicate"):
            router.add_host(InProcTransport(h1, label="m1"))
        router.get_TOAs(files[:2], gmodel, timeout=300, name="A")
        assert router.stats()["m1"]["state"] == HEALTHY
        assert router.remove_host("m0") is True
        assert router.remove_host("m0") is False
        assert router.host_labels() == ["m1"]
        res = router.get_TOAs(files[2:], gmodel, timeout=300, name="B")
        assert len(res.TOA_list) == 4
        assert router.stats()["m1"]["n_requests"] >= 1
        router.close()

    # fleet file over REAL listeners: initial join, then an edit
    # removes one and an unreachable entry is retried, not fatal
    with ToaServer(nsub_batch=8, max_wait_ms=30, quiet=True) as srv:
        with TransportServer(srv, port=0) as lis_a, \
                TransportServer(srv, port=0) as lis_b:
            ffile = tmp_path / "fleet.txt"
            ffile.write_text(
                f"# fleet\n127.0.0.1:{lis_a.port}\n"
                f"127.0.0.1:{lis_b.port}\n127.0.0.1:9\n")
            router = ToaRouter(fleet_file=str(ffile), probe_ms=500)
            labels = set(router.host_labels())
            assert f"127.0.0.1:{lis_a.port}" in labels
            assert f"127.0.0.1:{lis_b.port}" in labels
            assert "127.0.0.1:9" not in labels  # unreachable: retried
            ffile.write_text(f"127.0.0.1:{lis_a.port}\n")
            router._watcher.resync()
            assert router.host_labels() == [f"127.0.0.1:{lis_a.port}"]
            router.close()
    with pytest.raises(ValueError, match="no host endpoints"):
        ToaRouter([])


# ---------------------------------------------------------------------------
# exactly-once failover
# ---------------------------------------------------------------------------

def test_failover_redispatches_mid_fit(campaign, tmp_path):
    """Kill a host with a request in flight: the router re-places it
    on the survivor with the dead host excluded, the .tim is
    byte-identical to one-shot, and zero requests are lost or
    duplicated."""
    files, gmodel, refb = campaign
    trace = str(tmp_path / "kill.jsonl")
    with ToaServer(nsub_batch=8, max_wait_ms=30, quiet=True) as h0, \
            ToaServer(nsub_batch=8, max_wait_ms=30, quiet=True) as h1:
        k0 = _Killable(InProcTransport(h0, label="k0"))
        router = ToaRouter([k0, InProcTransport(h1, label="k1")],
                           telemetry=trace)
        tim = str(tmp_path / "killed.tim")
        rh = router.submit(files[:2], gmodel, tim_out=tim, name="F0")
        assert rh.host.label == "k0"
        k0.killed = True   # dies before the result is collected
        res = rh.result(300)
        stats = router.stats()
        router.close()
    assert len(res.TOA_list) == 4
    assert open(tim, "rb").read() == refb
    assert stats["k0"]["state"] == DEAD
    assert all(st["outstanding"] == 0 for st in stats.values())
    _, events = telemetry.validate_trace(trace)
    fo = [e for e in events if e["type"] == "route_failover"]
    assert len(fo) == 1 and fo[0]["dead_host"] == "k0"
    done = [e for e in events if e["type"] == "route_done"]
    assert len(done) == 1 and done[0]["error"] is None
    summary = telemetry.report(trace, file=io.StringIO())
    assert summary["n_failover"] == 1
    assert summary["fleet_states"]["k0"] == "DEAD"


def test_failover_collects_durable_tim_without_refit(campaign,
                                                     tmp_path):
    """The exactly-once core: a request whose .tim sentinels all
    landed before its host died is COLLECTED from the file — the
    survivor fits nothing, the bytes are untouched, and the recovered
    result re-serializes byte-identically (with the documented NaN
    DeltaDM summary and recovered_from_tim marker)."""
    files, gmodel, refb = campaign
    trace = str(tmp_path / "durable.jsonl")
    with ToaServer(nsub_batch=8, max_wait_ms=30, quiet=True) as h0, \
            ToaServer(nsub_batch=8, max_wait_ms=30, quiet=True) as h1:
        k0 = _Killable(InProcTransport(h0, label="k0"))
        router = ToaRouter([k0, InProcTransport(h1, label="k1")],
                           telemetry=trace)
        tim = str(tmp_path / "durable.tim")
        rh = router.submit(files[:2], gmodel, tim_out=tim, name="D0")
        deadline = time.monotonic() + 120
        while not tim_complete(tim, files[:2]):
            assert time.monotonic() < deadline, "tim never landed"
            time.sleep(0.05)
        k0.killed = True   # dies AFTER completion, BEFORE collection
        res = rh.result(300)
        survivor = router.stats()["k1"]
        router.close()
    assert res.recovered_from_tim is True
    assert len(res.TOA_list) == 4
    assert res.DM0s == [None, None]
    assert all(np.isnan(v) for v in res.DeltaDM_means)
    assert open(tim, "rb").read() == refb  # untouched
    assert survivor["n_requests"] == 0     # NEVER re-fit
    # the recovered payload re-serializes byte-identically
    tim2 = str(tmp_path / "reserialized.tim")
    write_tim_result(res, tim2)
    assert open(tim2, "rb").read() == refb
    _, events = telemetry.validate_trace(trace)
    fo = [e for e in events if e["type"] == "route_failover"]
    assert [e["action"] for e in fo] == ["collected"]
    summary = telemetry.report(trace, file=io.StringIO())
    assert summary["n_failover_collected"] == 1


# ---------------------------------------------------------------------------
# hedged requests
# ---------------------------------------------------------------------------

def test_hedged_requests_byte_identical_and_accounted(campaign,
                                                      tmp_path):
    """hedge_ms=0 forces a hedge on every request: first completion
    wins, .tim bytes match the one-shot reference exactly (the loser's
    side file is discarded), loads drain to zero, and the route ledger
    records the hedge."""
    files, gmodel, refb = campaign
    trace = str(tmp_path / "hedge.jsonl")
    with ToaServer(nsub_batch=8, max_wait_ms=30, quiet=True) as h0, \
            ToaServer(nsub_batch=8, max_wait_ms=30, quiet=True) as h1:
        router = ToaRouter([InProcTransport(h0, label="g0"),
                            InProcTransport(h1, label="g1")],
                           hedge_ms=0.0, telemetry=trace)
        tim = str(tmp_path / "hedged.tim")
        res = router.get_TOAs(files[:2], gmodel, timeout=300,
                              tim_out=tim, name="H0")
        stats = router.stats()
    # read the .tim AFTER the servers drained: a slow primary may
    # rewrite it post-collection — with identical bytes
    router.close()
    assert len(res.TOA_list) == 4
    assert open(tim, "rb").read() == refb
    # the hedge loser writes NOTHING host-side (no side files, no
    # two-writers-on-one-path window)
    assert not os.path.exists(tim + ".hedge")
    assert not os.path.exists(tim + ".tmp~")
    assert all(st["outstanding"] == 0 for st in stats.values())
    _, events = telemetry.validate_trace(trace)
    hedges = [e for e in events if e["type"] == "route_hedge"]
    assert len(hedges) == 1
    assert hedges[0]["primary"] != hedges[0]["host"]
    done = [e for e in events if e["type"] == "route_done"]
    assert done[0]["hedged"] is True and done[0]["error"] is None
    summary = telemetry.report(trace, file=io.StringIO())
    assert summary["n_hedge"] == 1


# ---------------------------------------------------------------------------
# the codec (no-shared-fs) lane + codec roundtrip properties
# ---------------------------------------------------------------------------

def test_codec_lane_router_writes_tim_over_socket(campaign, tmp_path):
    """write_tim='router' over the REAL wire: the serving host writes
    nothing, the full payload crosses the socket, and the
    router-written .tim is byte-identical to the shared-fs lane."""
    files, gmodel, refb = campaign
    with ToaServer(nsub_batch=8, max_wait_ms=30, quiet=True) as srv:
        with TransportServer(srv, port=0) as listener:
            router = ToaRouter(
                [SocketTransport(f"127.0.0.1:{listener.port}")],
                write_tim="router")
            tim = str(tmp_path / "codec.tim")
            res = router.get_TOAs(files[:2], gmodel, timeout=300,
                                  tim_out=tim, name="C0")
            router.close()
    assert res.tim_out == tim
    assert open(tim, "rb").read() == refb
    with pytest.raises(ValueError, match="write_tim"):
        ToaRouter([InProcTransport(object(), label="x")],
                  write_tim="nowhere")


def test_codec_roundtrip_property(campaign, tmp_path):
    """Property-style roundtrip of the full TOA result payload
    (ISSUE 13 satellite): randomized MJD (int day, f64 frac)
    exactness, inf frequency, the int/float/str/bool flag trichotomy
    with numpy scalar narrowing, and empty-archive results — every
    trial must re-serialize to identical .tim bytes through
    write_tim_result."""
    from pulseportraiture_tpu.io.tim import TOA, toa_string

    rng = np.random.default_rng(1234)
    flag_makers = [
        lambda r: int(r.integers(-5, 2000)),
        lambda r: np.int64(r.integers(0, 1 << 40)),
        lambda r: float(r.normal() * 10.0 ** int(r.integers(-6, 6))),
        lambda r: np.float32(r.normal()),
        lambda r: np.float64(r.normal()),
        lambda r: "GUPPI_" + str(r.integers(0, 9)),
        lambda r: bool(r.integers(0, 2)),
        lambda r: np.bool_(r.integers(0, 2)),
    ]
    for trial in range(50):
        n_arch = int(rng.integers(1, 4))
        order, toas = [], []
        for a in range(n_arch):
            datafile = f"/data/ep{trial}_{a}.fits"
            order.append(datafile)
            for _s in range(int(rng.integers(0, 3))):
                flags = {f"f{k}": flag_makers[
                    int(rng.integers(0, len(flag_makers)))](rng)
                    for k in range(int(rng.integers(0, 5)))}
                freq = (np.inf if rng.random() < 0.2
                        else float(rng.uniform(100, 3000)))
                toas.append(TOA(
                    datafile, freq,
                    MJD(int(rng.integers(40000, 60000)),
                        float(rng.random())),
                    float(abs(rng.normal()) + 1e-3), "GBT", "1",
                    DM=(None if rng.random() < 0.3
                        else float(rng.uniform(0, 300))),
                    DM_error=(None if rng.random() < 0.3
                              else float(abs(rng.normal()) * 1e-2)),
                    flags=flags))
        res = DataBunch(
            TOA_list=toas, order=order,
            DM0s=[None if rng.random() < 0.5
                  else float(rng.uniform(0, 300))
                  for _ in order],
            DeltaDM_means=[float(rng.normal()) for _ in order],
            DeltaDM_errs=[float(abs(rng.normal())) for _ in order],
            tim_out=None, n_skipped=0)
        wire = json.dumps(encode_result(res),
                          separators=(",", ":"))
        back = decode_result(json.loads(wire))
        assert back.order == order
        assert back.DM0s == res.DM0s
        assert back.DeltaDM_means == res.DeltaDM_means
        for ta, tb in zip(res.TOA_list, back.TOA_list):
            assert (ta.MJD.day, ta.MJD.frac) == (tb.MJD.day,
                                                 tb.MJD.frac)
            assert tb.frequency == ta.frequency  # incl. inf
            assert toa_string(tb) == toa_string(ta)
            for k, v in ta.flags.items():
                w = tb.flags[k]
                if isinstance(v, (bool, np.bool_)):
                    assert isinstance(w, bool)
                elif isinstance(v, (int, np.integer)):
                    assert isinstance(w, int) and w == int(v)
                elif isinstance(v, (float, np.floating)):
                    assert isinstance(w, float)
                else:
                    assert w == v
        # codec-lane .tim bytes == shared-fs-lane bytes: the server
        # writes per-archive write_TOAs + sentinel, and so must the
        # router's writer from the DECODED payload
        a = str(tmp_path / f"srv{trial}.tim")
        b = str(tmp_path / f"rtr{trial}.tim")
        from pulseportraiture_tpu.io.tim import write_TOAs
        from pulseportraiture_tpu.pipeline.stream import _DONE_PREFIX

        open(a, "w").close()
        groups = {d: [t for t in toas if t.archive == d]
                  for d in order}
        for d in order:
            write_TOAs(groups[d], outfile=a, append=True)
            with open(a, "a") as fh:
                fh.write(_DONE_PREFIX + os.path.abspath(d) + "\n")
        write_tim_result(back, b)
        assert open(b, "rb").read() == open(a, "rb").read(), trial
    # the durable-.tim reader inverts the writer, empty archives incl.
    assert read_tim_result(b).order == order
    # a real campaign result survives the recover-and-reserialize loop
    files, gmodel, refb = campaign
    one = stream_wideband_TOAs(files[:2], gmodel, nsub_batch=8,
                               quiet=True)
    tim = str(tmp_path / "real.tim")
    one.tim_out = None
    write_tim_result(one, tim)
    assert open(tim, "rb").read() == refb
    rec = read_tim_result(tim)
    tim2 = str(tmp_path / "real2.tim")
    write_tim_result(rec, tim2)
    assert open(tim2, "rb").read() == refb


# ---------------------------------------------------------------------------
# multi-tenant QoS
# ---------------------------------------------------------------------------

def test_admission_queue_tenant_qos_units():
    """Per-tenant quotas reject retryably (naming the tenant and the
    knob), oversize-for-quota requests are terminal, the weighted-fair
    scheduler serves lanes in weight proportion, and an idle lane
    cannot bank credit."""
    q = AdmissionQueue(100, tenant_quota={"bulk": 4},
                       tenant_weight={"fast": 4.0, "bulk": 1.0})
    for i in range(4):
        q.submit(ServeRequest([f"b{i}.fits"], "m", tenant="bulk"))
    with pytest.raises(Exception, match="over quota") as ei:
        q.submit(ServeRequest(["b4.fits"], "m", tenant="bulk"))
    assert ei.value.retryable is True
    assert "bulk" in str(ei.value)
    with pytest.raises(Exception, match="split it") as ei:
        q.submit(ServeRequest([f"x{i}.fits" for i in range(5)], "m",
                              tenant="bulk"))
    assert ei.value.retryable is False
    # other tenants are unaffected by bulk's quota
    for i in range(4):
        q.submit(ServeRequest([f"f{i}.fits"], "m", tenant="fast"))
    snap = q.tenant_snapshot()
    assert snap["bulk"]["queued"] == 4
    assert snap["fast"]["pending_archives"] == 4
    # weighted-fair: fast (weight 4) gets ~4 pops per bulk pop
    order = [q.get(0.01).tenant for _ in range(8)]
    assert order.count("fast") == 4 and order.count("bulk") == 4
    assert order[1:5] == ["fast"] * 4, order  # fast never starved
    # quota credit returns per-tenant via release
    assert q.pending_archives == 8
    q.release(4, tenant="bulk")
    q.submit(ServeRequest(["b5.fits"], "m", tenant="bulk"))
    # an idle lane waking up starts at the CURRENT virtual time: it
    # must not monopolize the scheduler to catch up
    q2 = AdmissionQueue(100, tenant_weight={"a": 1.0, "b": 1.0})
    for i in range(4):
        q2.submit(ServeRequest([f"a{i}.fits"], "m", tenant="a"))
    assert [q2.get(0.01).tenant for _ in range(2)] == ["a", "a"]
    for i in range(2):
        q2.submit(ServeRequest([f"b{i}.fits"], "m", tenant="b"))
    order = [q2.get(0.01).tenant for _ in range(4)]
    # without the wake-up clamp this would be ['b','b','a','a'] (b
    # "catching up" from vtime 0); with it the lanes interleave
    assert order == ["a", "b", "a", "b"], order


def test_tenant_qos_end_to_end_with_trace(campaign, tmp_path):
    """tenant= rides submit -> wire -> AdmissionQueue lane -> the
    request_done/route_done events, and the pptrace fleet section
    reports the per-tenant latency split."""
    files, gmodel, _ = campaign
    trace = str(tmp_path / "tenant.jsonl")
    with ToaServer(nsub_batch=8, max_wait_ms=30, quiet=True,
                   telemetry=trace,
                   tenant_quota={"bulk": 8}) as srv:
        with TransportServer(srv, port=0) as listener:
            router = ToaRouter(
                [SocketTransport(f"127.0.0.1:{listener.port}")])
            ha = router.submit(files[:2], gmodel, name="big",
                               tenant="bulk")
            hb = router.submit(files[2:3], gmodel, name="small",
                               tenant="interactive")
            ha.result(300)
            hb.result(300)
            router.close()
    _, events = telemetry.validate_trace(trace)
    sub = {e["req"]: e.get("tenant") for e in events
           if e["type"] == "request_submit"}
    assert sub == {"big": "bulk", "small": "interactive"}
    done = {e["req"]: e.get("tenant") for e in events
            if e["type"] == "request_done"}
    assert done == {"big": "bulk", "small": "interactive"}
    summary = telemetry.report(trace, file=io.StringIO())
    assert set(summary["tenant_latency"]) == {"bulk", "interactive"}
    for rec in summary["tenant_latency"].values():
        assert rec["n"] == 1 and rec["p99_s"] >= rec["p50_s"] > 0


# ---------------------------------------------------------------------------
# refit-aware routing (ROADMAP item 4 tail)
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def rfi_pair(tmp_path_factory):
    """One contaminated + one clean archive (the test_quality
    injector recipe) plus their zap-then-fit oracle .tim."""
    from pulseportraiture_tpu.io.psrfits import load_data
    from pulseportraiture_tpu.pipeline.zap import get_zap_channels
    from pulseportraiture_tpu.synth import inject_rfi

    root = tmp_path_factory.mktemp("fleet_rfi")
    model = default_test_model(1500.0)
    gmodel = str(root / "model.gmodel")
    write_gmodel(model, gmodel, quiet=True)
    files = []
    specs = [dict(tone_channels=[3, 11], tone_white=8.0,
                  tone_structured=60.0,
                  bursts=[(1, [20, 21], 20.0)]), None]
    for i, spec in enumerate(specs):
        path = str(root / f"ep{i}.fits")
        make_fake_pulsar(model, PAR, outfile=path, nsub=2, nchan=32,
                         nbin=128, nu0=1500.0, bw=800.0, tsub=60.0,
                         phase=0.01 * i, dDM=1e-4 * (i - 1),
                         noise_stds=0.05, dedispersed=False,
                         quiet=True, rng=300 + i)
        if spec:
            inject_rfi(path, rng=40 + i, **spec)
        files.append(path)
    d = load_data(files[0], dedisperse=False, dededisperse=True,
                  pscrunch=True, quiet=True)
    zl = get_zap_channels(d, device=False)
    oracle = str(root / "oracle.tim")
    stream_wideband_TOAs(files, gmodel, nsub_batch=8, quiet=True,
                         tim_out=oracle, zap_channels={files[0]: zl})
    return files, gmodel, open(oracle, "rb").read()


def test_refit_aware_routing_moves_host_and_matches_oracle(rfi_pair,
                                                           tmp_path):
    """A gate-tripping archive collected through the router is
    zap-and-refit EXACTLY once on the least-loaded HEALTHY host (the
    refit event carries the host move), the merged .tim equals the
    offline zap-then-fit oracle byte-for-byte, and a clean corpus is
    untouched with the loop on."""
    files, gmodel, oracleb = rfi_pair
    trace = str(tmp_path / "refit.jsonl")
    with ToaServer(nsub_batch=8, max_wait_ms=30, quiet=True) as h0, \
            ToaServer(nsub_batch=8, max_wait_ms=30, quiet=True) as h1:
        router = ToaRouter([InProcTransport(h0, label="r0"),
                            InProcTransport(h1, label="r1")],
                           quality_refit=True, telemetry=trace)
        tim = str(tmp_path / "routed.tim")
        res = router.get_TOAs(files, gmodel, timeout=600,
                              tim_out=tim, name="R")
        # clean request: no refit, bytes as served
        clean_tim = str(tmp_path / "clean.tim")
        router.get_TOAs(files[1:], gmodel, timeout=600,
                        tim_out=clean_tim, name="CL")
        router.close()
    assert len(res.TOA_list) == 4
    assert open(tim, "rb").read() == oracleb
    ref_clean = str(tmp_path / "ref_clean.tim")
    stream_wideband_TOAs(files[1:], gmodel, nsub_batch=8, quiet=True,
                         tim_out=ref_clean)
    assert open(clean_tim, "rb").read() == \
        open(ref_clean, "rb").read()
    _, events = telemetry.validate_trace(trace)
    refits = [e for e in events if e["type"] == "refit"]
    assert len(refits) == 1      # exactly once, contaminated only
    ev = refits[0]
    assert ev["datafile"] == files[0]
    assert ev["n_channels"] > 0
    assert ev["improved"] is True and ev["gof_after"] < \
        ev["gof_before"]
    # the host move rides the event (host_from -> host); with both
    # hosts idle the least-loaded HEALTHY host is a valid target
    # either way — the fields must exist and name fleet members
    assert ev["host_from"] in ("r0", "r1")
    assert ev["host"] in ("r0", "r1")


# ---------------------------------------------------------------------------
# env hooks
# ---------------------------------------------------------------------------

def test_fleet_env_hooks(monkeypatch):
    """PPT_ROUTER_PROBE_MS / PPT_ROUTER_HEDGE_MS /
    PPT_ROUTER_FLEET_FILE / PPT_SERVE_TENANT_QUOTA /
    PPT_SERVE_TENANT_WEIGHT: registered in KNOWN_PPT_ENV, strict
    parses, loud errors, did-you-mean on typos."""
    old = (config.router_probe_ms, config.router_hedge_ms,
           config.router_fleet_file, config.serve_tenant_quota,
           config.serve_tenant_weight)
    try:
        for name in ("PPT_ROUTER_PROBE_MS", "PPT_ROUTER_HEDGE_MS",
                     "PPT_ROUTER_FLEET_FILE",
                     "PPT_SERVE_TENANT_QUOTA",
                     "PPT_SERVE_TENANT_WEIGHT"):
            assert name in config.KNOWN_PPT_ENV
        monkeypatch.setenv("PPT_ROUTER_PROBE_MS", "250")
        monkeypatch.setenv("PPT_ROUTER_HEDGE_MS", "1500")
        monkeypatch.setenv("PPT_ROUTER_FLEET_FILE", "/tmp/fleet.txt")
        monkeypatch.setenv("PPT_SERVE_TENANT_QUOTA",
                           "bulk:32,interactive:8,*:16")
        monkeypatch.setenv("PPT_SERVE_TENANT_WEIGHT",
                           "interactive:4,bulk:1")
        changed = config.env_overrides()
        for key in ("router_probe_ms", "router_hedge_ms",
                    "router_fleet_file", "serve_tenant_quota",
                    "serve_tenant_weight"):
            assert key in changed
        assert config.router_probe_ms == 250.0
        assert config.router_hedge_ms == 1500.0
        assert config.router_fleet_file == "/tmp/fleet.txt"
        assert config.serve_tenant_quota == {"bulk": 32,
                                             "interactive": 8,
                                             "*": 16}
        assert config.serve_tenant_weight == {"interactive": 4.0,
                                              "bulk": 1.0}
        monkeypatch.setenv("PPT_SERVE_TENANT_QUOTA", "12")
        config.env_overrides()
        assert config.serve_tenant_quota == 12
        for name, off in (("PPT_ROUTER_HEDGE_MS", None),
                          ("PPT_ROUTER_FLEET_FILE", None),
                          ("PPT_SERVE_TENANT_QUOTA", None),
                          ("PPT_SERVE_TENANT_WEIGHT", None)):
            monkeypatch.setenv(name, "off")
        config.env_overrides()
        assert config.router_hedge_ms is None
        assert config.router_fleet_file is None
        assert config.serve_tenant_quota is None
        assert config.serve_tenant_weight is None
        for name, bad in (("PPT_ROUTER_PROBE_MS", "0"),
                          ("PPT_ROUTER_PROBE_MS", "soon"),
                          ("PPT_ROUTER_HEDGE_MS", "-1"),
                          ("PPT_SERVE_TENANT_QUOTA", "bulk:0"),
                          ("PPT_SERVE_TENANT_QUOTA", "bulk:x"),
                          ("PPT_SERVE_TENANT_QUOTA", "a:1,a:2"),
                          ("PPT_SERVE_TENANT_WEIGHT", "3.0"),
                          ("PPT_SERVE_TENANT_WEIGHT", ":2")):
            monkeypatch.setenv(name, bad)
            with pytest.raises(ValueError, match=name):
                config.env_overrides()
            monkeypatch.delenv(name)
        # did-you-mean: a typo'd knob warns with the close match
        import pulseportraiture_tpu.config as cfgmod

        cfgmod._warned_unknown_ppt.discard("PPT_ROUTER_PROBE_M")
        monkeypatch.setenv("PPT_ROUTER_PROBE_M", "100")
        import contextlib
        import io as _io

        err = _io.StringIO()
        with contextlib.redirect_stderr(err):
            config.env_overrides()
        assert "PPT_ROUTER_PROBE_MS" in err.getvalue()
    finally:
        (config.router_probe_ms, config.router_hedge_ms,
         config.router_fleet_file, config.serve_tenant_quota,
         config.serve_tenant_weight) = old
