"""ISSUE 11: the fleet timing stage — serial vs batched wideband GLS
at fleet scale.

The timing stage was the last per-pulsar-serial production stage: a
PTA campaign ends with N_psr independent linear solves, each
milliseconds of f64 math behind a full dispatch floor.  This bench
measures the R12-style batching applied to it (timing/fleet.py):

* **serial arm** — one device solve dispatch PER PULSAR (the same
  padded pow2 program the batched lane compiles, batched=False);
* **batched arm** — one dispatch PER (rows x params) BUCKET: the
  whole fleet's systems zero-padded into a handful of pow2 classes;
* **host oracle** — per-pulsar NumPy solves (timing/gls.gls_solve_np,
  device=False), the algorithm reference.

The headline is the DISPATCH-COUNT REDUCTION (serial pays N_psr
dispatches, batched pays n_buckets — the chip-side win is the
dispatch floor times that ratio; CPU walls are reported honestly but
a millisecond lstsq on one core has nothing to amortize).  The digit
gate (batched-vs-SERIAL <= 1e-10 on every fitted parameter, scaled by
max(|value|, error) — same padded program at B=1, so any excess is
genuine batching leakage) is enforced EVERY run, tiny smoke shapes
included, plus a looser <= 1e-8 cross-library check against the NumPy
oracle.  Under PPT_TELEMETRY the batched arm's trace is
schema-validated and the "timing" section summary is checked.

Fleet fixture: synthetic TimTOA campaigns straight from parfiles
(synth.fake_timing_campaign — no archives), a mix of ELL1, BT and
isolated pulsars with heterogeneous epoch counts so the pow2
bucketing is actually exercised.  Shapes via PPT_NPSR / PPT_NE.
"""

import json
import os
import sys
import time

sys.path.insert(0, __file__.rsplit("/", 2)[0])

DIGIT_GATE = 1e-10


def _fleet(npsr, nep, rng_base=400):
    from pulseportraiture_tpu.synth import fake_timing_campaign

    jobs = []
    for i in range(npsr):
        par = {"PSR": f"B{i:03d}", "F0": str(180.0 + 17.3 * i),
               "PEPOCH": "55500", "DM": str(8.0 + 1.5 * i)}
        kind = i % 3
        if kind == 0:
            par.update({"BINARY": "ELL1", "PB": str(0.4 + 0.07 * i),
                        "A1": str(0.04 + 0.005 * i),
                        "TASC": "55499.13", "EPS1": "1.5e-6",
                        "EPS2": "-6e-7"})
        elif kind == 1:
            par.update({"BINARY": "BT", "PB": str(0.9 + 0.05 * i),
                        "A1": str(0.3 + 0.02 * i), "T0": "55499.4",
                        "ECC": "0.12", "OM": str(20.0 + 10.0 * i)})
        truth = {"F0": float(par["F0"]) * (1.0 + 1e-13)}
        if kind != 2:
            truth["PB"] = float(par["PB"]) + 2e-9
        toas, _ = fake_timing_campaign(
            par, truth=truth, n_epochs=nep + (i % 2),
            toas_per_epoch=2, span_days=90.0, toa_err_us=0.1,
            dmx=2e-4, rng=rng_base + i)
        jobs.append((par["PSR"], toas, par))
    return jobs


def main():
    import pulseportraiture_tpu  # noqa: F401
    from pulseportraiture_tpu import config, telemetry
    from pulseportraiture_tpu.timing import TimingJob, fleet_gls_fit

    config.env_overrides()
    NPSR = int(os.environ.get("PPT_NPSR", 16))
    NEP = int(os.environ.get("PPT_NE", 8))
    trace_path = config.telemetry_path

    jobs = [TimingJob(*spec) for spec in _fleet(NPSR, NEP)]

    # host oracle (per-pulsar NumPy)
    t0 = time.perf_counter()
    host = fleet_gls_fit(jobs, device=False, quiet=True)
    wall_host = time.perf_counter() - t0

    # warm both device program classes before timing (compile cost is
    # a separate, amortized-once story)
    fleet_gls_fit(jobs, device=True, batched=True, quiet=True)
    fleet_gls_fit(jobs, device=True, batched=False, quiet=True)

    t0 = time.perf_counter()
    serial = fleet_gls_fit(jobs, device=True, batched=False,
                           quiet=True)
    wall_serial = time.perf_counter() - t0
    t0 = time.perf_counter()
    batched = fleet_gls_fit(jobs, device=True, batched=True,
                            telemetry=trace_path, quiet=True)
    wall_batched = time.perf_counter() - t0

    # THE digit gate (the acceptance criterion): batched vs the
    # per-pulsar SERIAL solve — same padded program at B=1, so any
    # excess is genuine batching leakage, not library rounding.
    # Every pulsar, every fitted parameter (incl. DMX), scaled by
    # max(|value|, error).
    def _max_delta(a, b):
        worst = 0.0
        for name in a.pulsars:
            ra, rc = a.results[name], b.results[name]
            pairs = [(ra.params[k], rc.params[k], ra.param_errs[k])
                     for k in ra.params]
            pairs += list(zip(ra.dmx, rc.dmx, ra.dmx_errs))
            for va, vc, err in pairs:
                scale = max(abs(vc), float(err), 1e-300)
                worst = max(worst, abs(va - vc) / scale)
        return worst

    digit_max = _max_delta(batched, serial)
    digit_ok = digit_max <= DIGIT_GATE
    assert digit_ok, (
        f"batched-vs-serial digit gate FAILED: {digit_max:.3e} > "
        f"{DIGIT_GATE}")
    # cross-library check against the NumPy oracle: XLA's batched SVD
    # and LAPACK's round differently at the last digits of a marginal
    # system, so this gate is looser — it guards the ALGORITHM
    # (column-normalized normal equations), not the rounding
    digit_max_host = _max_delta(batched, host)
    assert digit_max_host <= 1e-8, (
        f"batched-vs-host oracle drift: {digit_max_host:.3e} > 1e-8")

    reduction = serial.n_dispatches / max(batched.n_dispatches, 1)

    summary = None
    if trace_path:
        telemetry.validate_trace(trace_path)
        with open(os.devnull, "w") as sink:
            summary = telemetry.report(trace_path, file=sink)
        assert summary["n_timing_fit"] == batched.n_dispatches, summary
        assert summary["n_timing_pulsars"] == NPSR, summary

    print(json.dumps({
        "metric": f"fleet GLS serial-vs-batched dispatch reduction: "
                  f"{NPSR} pulsars (ELL1/BT/isolated mix), ~{NEP} "
                  "epochs each",
        "value": round(reduction, 2),
        "unit": "x fewer dispatches",
        "pulsars": NPSR,
        "serial_dispatches": serial.n_dispatches,
        "batched_dispatches": batched.n_dispatches,
        "wall_host_s": round(wall_host, 4),
        "wall_serial_s": round(wall_serial, 4),
        "wall_batched_s": round(wall_batched, 4),
        "speedup_vs_serial": round(wall_serial / max(wall_batched,
                                                     1e-9), 3),
        "digit_max": digit_max,
        "digit_max_vs_host": digit_max_host,
        "digit_gate_ok": bool(digit_ok),
        "trace_validated": bool(summary is not None),
    }))


if __name__ == "__main__":
    main()
