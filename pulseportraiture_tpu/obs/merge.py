"""Cross-host trace stitching: ``pptrace merge``.

Takes one router trace plus N host traces (any order — roles are
detected from the event stream) and reconstructs, per ``trace_id``,
the request's life across processes: router placement -> host queue
wait -> fit dispatch(es) -> serve -> wire + collect, with hedges and
failovers called out and the critical-path stage named.

Cross-trace clock alignment uses each manifest's ``t0_unix`` wall
anchor plus the per-event monotonic offset ``t``; on one machine (the
test/bench lane) that is exact, across real hosts it is as good as the
hosts' NTP discipline — sub-span ordering within one trace is always
exact regardless.
"""

import json
import os


def _wall(manifest, event):
    return manifest["t0_unix"] + event["t"]


def _load_all(paths):
    # local import: telemetry.main imports this module for the merge
    # subcommand, so the reverse import must stay off module scope
    from pulseportraiture_tpu.telemetry import load_trace

    traces = []
    for p in paths:
        manifest, events = load_trace(p)
        kinds = {e.get("type") for e in events}
        role = ("router" if any(k and k.startswith("route_")
                                for k in kinds)
                or manifest.get("run") == "pproute" else "host")
        traces.append({"path": str(p),
                       "label": os.path.basename(str(p)),
                       "manifest": manifest, "events": events,
                       "role": role})
    return traces


def merge_traces(paths):
    """Stitch traces into per-request timelines keyed by trace_id.

    Returns a dict with ``requests`` (trace_id -> timeline), a
    ``trace_ids`` -> request-name map, and coverage bookkeeping; raises
    ValueError when no trace carries trace-ids at all (pre-ISSUE-20
    traces have nothing to join on)."""
    traces = _load_all(paths)
    reqs = {}

    def entry(tid):
        r = reqs.get(tid)
        if r is None:
            r = reqs[tid] = {
                "trace_id": tid, "req": None, "tenant": None,
                "t0_wall": None, "router_wall_s": None,
                "spans": [], "hedges": [], "failovers": [],
                "coalesces": [], "cache_hit": False, "error": None}
        return r

    saw_any_tid = False
    for tr in traces:
        man, label = tr["manifest"], tr["label"]
        for e in tr["events"]:
            et = e.get("type")
            if et == "batch_coalesce":
                for tid in (e.get("trace_ids") or ()):
                    saw_any_tid = True
                    entry(tid)["coalesces"].append(
                        {"t_wall": _wall(man, e), "where": label,
                         "seq": e.get("seq"), "rows": e.get("rows")})
                continue
            tid = e.get("trace_id")
            if not tid:
                continue
            saw_any_tid = True
            r = entry(tid)
            if et == "route_submit":
                name = e.get("req") or ""
                if r["req"] is None or not name.endswith(":refit"):
                    r["req"] = name.split(":refit")[0] or r["req"]
                r["tenant"] = e.get("tenant") or r["tenant"]
                t = _wall(man, e)
                if r["t0_wall"] is None or t < r["t0_wall"]:
                    r["t0_wall"] = t
                r["spans"].append(
                    {"stage": "route", "where": label,
                     "t_wall": t, "dur_s": None,
                     "host": e.get("host"),
                     "attempt": e.get("attempt")})
                if e.get("host") is None:
                    r["cache_hit"] = True
            elif et == "route_done":
                r["router_wall_s"] = e.get("wall_s")
                r["error"] = e.get("error") or r["error"]
                for s in reversed(r["spans"]):
                    if s["stage"] == "route" and s["dur_s"] is None:
                        s["dur_s"] = e.get("wall_s")
                        break
            elif et == "route_hedge":
                r["hedges"].append(
                    {"t_wall": _wall(man, e),
                     "primary": e.get("primary"),
                     "host": e.get("host")})
            elif et == "route_failover":
                r["failovers"].append(
                    {"t_wall": _wall(man, e),
                     "dead_host": e.get("dead_host"),
                     "action": e.get("action")})
            elif et == "request_submit":
                t = _wall(man, e)
                if r["t0_wall"] is None or t < r["t0_wall"]:
                    r["t0_wall"] = t
                r["spans"].append(
                    {"stage": "serve", "where": label, "t_wall": t,
                     "dur_s": None, "queue_s": None})
            elif et == "request_done":
                r["tenant"] = e.get("tenant") or r["tenant"]
                for s in reversed(r["spans"]):
                    if (s["stage"] == "serve" and s["where"] == label
                            and s["dur_s"] is None):
                        s["dur_s"] = e.get("wall_s")
                        s["queue_s"] = e.get("queue_s")
                        s["error"] = e.get("error")
                        break
            elif et == "cache_hit":
                r["cache_hit"] = True

    if not saw_any_tid:
        raise ValueError(
            "no trace_id fields in any input trace — these traces "
            "predate distributed tracing (re-run with telemetry on a "
            "current build)")

    for r in reqs.values():
        r["spans"].sort(key=lambda s: s["t_wall"])
        # critical path: the dominant stage of the completed lifecycle
        serve_spans = [s for s in r["spans"]
                       if s["stage"] == "serve" and s["dur_s"]
                       is not None]
        segs = {}
        if serve_spans:
            last = serve_spans[-1]
            q = last.get("queue_s") or 0.0
            segs["queue"] = q
            segs["serve"] = max((last["dur_s"] or 0.0) - q, 0.0)
            if r["router_wall_s"] is not None:
                segs["wire+collect"] = max(
                    r["router_wall_s"] - (last["dur_s"] or 0.0), 0.0)
        elif r["cache_hit"]:
            segs["cache"] = r["router_wall_s"] or 0.0
        r["segments"] = {k: round(v, 6) for k, v in segs.items()}
        r["critical"] = (max(segs, key=segs.get) if segs else None)
        r["n_host_spans"] = len(
            [s for s in r["spans"] if s["stage"] == "serve"])
        r["hedged"] = bool(r["hedges"])

    return {
        "n_traces": len(traces),
        "traces": [{"label": t["label"], "role": t["role"],
                    "run": t["manifest"].get("run")} for t in traces],
        "n_requests": len(reqs),
        "requests": reqs,
    }


def format_merge(merged, file=None):
    """Render a merged timeline as text (the pptrace merge default)."""
    import sys

    out = file or sys.stdout
    p = lambda s="": print(s, file=out)  # noqa: E731
    roles = ", ".join(f"{t['label']}({t['role']})"
                      for t in merged["traces"])
    p(f"merged {merged['n_traces']} traces: {roles}")
    p(f"requests: {merged['n_requests']}")
    order = sorted(merged["requests"].values(),
                   key=lambda r: r["t0_wall"] or 0.0)
    for r in order:
        wall = (f"{r['router_wall_s']:.3f} s"
                if r["router_wall_s"] is not None else "?")
        flags = []
        if r["cache_hit"]:
            flags.append("cache-hit")
        if r["hedged"]:
            flags.append("hedged")
        if r["failovers"]:
            flags.append(f"failover x{len(r['failovers'])}")
        if r["error"]:
            flags.append(f"ERROR {r['error']}")
        tag = f"  [{', '.join(flags)}]" if flags else ""
        p(f"req {r['req'] or '?'} trace={r['trace_id']} "
          f"tenant={r['tenant'] or '?'} total {wall} "
          f"critical={r['critical'] or '?'}{tag}")
        t0 = r["t0_wall"] or 0.0
        for s in r["spans"]:
            rel = s["t_wall"] - t0
            dur = (f"+{s['dur_s']:.3f}s" if s.get("dur_s") is not None
                   else "+?")
            if s["stage"] == "route":
                host = s.get("host") or "cache"
                p(f"    {rel:8.3f} {dur:>10}  route -> {host} "
                  f"(attempt {s.get('attempt')}) [{s['where']}]")
            else:
                q = s.get("queue_s")
                qs = f" queue {q:.3f}s" if q is not None else ""
                p(f"    {rel:8.3f} {dur:>10}  serve{qs} "
                  f"[{s['where']}]")
        for c in r["coalesces"]:
            p(f"    {c['t_wall'] - t0:8.3f}             coalesce "
              f"seq={c['seq']} rows={c['rows']} [{c['where']}]")
        for h in r["hedges"]:
            p(f"    {h['t_wall'] - t0:8.3f}             hedge "
              f"{h['primary']} -> {h['host']}")
        for f in r["failovers"]:
            p(f"    {f['t_wall'] - t0:8.3f}             failover "
              f"dead={f['dead_host']} action={f['action']}")


def main_merge(paths, as_json=False, file=None):
    """Entry point for ``pptrace merge``; returns the merged dict."""
    merged = merge_traces(paths)
    if as_json:
        import sys
        print(json.dumps(merged, sort_keys=True),
              file=file or sys.stdout)
    else:
        format_merge(merged, file=file)
    return merged
