"""Cross-archive streaming TOA measurement — the at-scale driver.

GetTOAs dispatches one batched fit per archive; on the tunneled TPU
runtime each dispatch has a ~100 ms floor, so a 1000-archive campaign
with modest per-archive subint counts is dispatch-bound, not
compute-bound.  This driver instead POOLS ok subints across archives
into shape buckets — keyed by (nchan, nbin, channel-frequency layout,
effective fit flags, and the template period when the template depends
on P) — and fires one large fused fit per full bucket, overlapping
archive IO with device compute via the same prefetch loader GetTOAs
uses.  Results are scattered back to their archives and returned in
archive order; only the few per-subint fields needed for TOA assembly
are retained, so host memory stays O(bucket), not O(campaign).

Scope: campaign configurations — wideband (phi[, DM]) fits, plus
scattering (fit_scat/log10_tau/scat_guess/fix_alpha as in GetTOAs).
GM / instrumental response / flux remain GetTOAs-only.  No-scattering
buckets take the complex-free f32 fast path on TPU backends
(config.use_fast_fit), scattering buckets the complex engine; subints
with a single usable channel are demoted to phase-only buckets (the
degenerate-geometry fallback, pptoas.py:519-527).

The reference has no analogue (strictly sequential archive loop,
pptoas.py:258); this is new capability enabled by the batched engine.
"""

import time

import jax.numpy as jnp
import numpy as np

from ..config import scattering_alpha
from ..fit.portrait import (FitFlags, fit_portrait_batch,
                            fit_portrait_batch_fast, use_fast_fit_default)
from ..io.tim import TOA, write_TOAs
from ..utils.bunch import DataBunch
from .models import TemplateModel
from .toas import (_is_metafile, _iter_archives, _read_metafile,
                   _validate_scat_guess, delta_dm_stats, load_for_toas,
                   scat_time_flags, snr_weighted_nu_fit)


class _Bucket:
    """Pending subints sharing one (layout, flags) key."""

    def __init__(self, freqs, nbin, modelx, flags):
        self.freqs = freqs          # (nchan,)
        self.nbin = int(nbin)
        self.modelx = modelx        # (nchan, nbin) template
        self.flags = flags          # effective FitFlags tuple
        self.ports = []             # each (nchan, nbin)
        self.noise = []             # each (nchan,)
        self.masks = []             # each (nchan,)
        self.Ps = []
        self.nu_fits = []
        self.theta0 = []            # each (5,)
        self.owners = []            # (archive_index, isub)

    def __len__(self):
        return len(self.ports)


def _flush(bucket, nu_ref_DM, max_iter, nsub_batch, results,
           log10_tau=False):
    """Fit every pending subint of a bucket in ONE dispatch and scatter
    the results into per-(archive, subint) records.  The batch is
    always padded to a multiple of nsub_batch so dispatch shapes stay
    canonical (each distinct shape costs an XLA compile)."""
    n = len(bucket)
    if n == 0:
        return 0.0, []
    pad = (-n) % nsub_batch
    idx0 = list(range(n)) + [0] * pad  # pad with copies of subint 0
    ports = np.stack([bucket.ports[i] for i in idx0])
    noise = np.stack([bucket.noise[i] for i in idx0])
    masks = np.stack([bucket.masks[i] for i in idx0])
    Ps = np.asarray([bucket.Ps[i] for i in idx0])
    nu_fit = np.asarray([bucket.nu_fits[i] for i in idx0])
    theta0 = np.stack([bucket.theta0[i] for i in idx0])
    flags = FitFlags(*bucket.flags)

    # scattering (fitted, or a fixed nonzero/log10 tau seed in a
    # degenerate lane of a scattering run) requires the complex engine
    scat = (flags[3] or flags[4] or log10_tau
            or bool(np.any(theta0[:, 3] != 0.0)))
    t0 = time.time()
    if not scat and use_fast_fit_default():
        ft = jnp.float32
        r = fit_portrait_batch_fast(
            jnp.asarray(ports, ft), jnp.asarray(bucket.modelx, ft),
            jnp.asarray(noise, ft), jnp.asarray(bucket.freqs, ft),
            jnp.asarray(Ps, ft), jnp.asarray(nu_fit, ft),
            nu_out=nu_ref_DM, theta0=jnp.asarray(theta0, ft),
            fit_flags=flags, chan_masks=jnp.asarray(masks, ft),
            max_iter=max_iter)
    else:
        r = fit_portrait_batch(
            jnp.asarray(ports),
            jnp.broadcast_to(jnp.asarray(bucket.modelx), ports.shape),
            jnp.asarray(noise), jnp.asarray(bucket.freqs),
            jnp.asarray(Ps), jnp.asarray(nu_fit),
            nu_out=nu_ref_DM, theta0=jnp.asarray(theta0),
            fit_flags=flags, chan_masks=jnp.asarray(masks),
            log10_tau=log10_tau, max_iter=max_iter)
    out = {k: np.asarray(v) for k, v in r._asdict().items()}
    dt = time.time() - t0
    resolved = list(bucket.owners)
    keys = ("phi", "phi_err", "DM", "DM_err", "nu_DM", "snr", "chi2",
            "dof", "nfeval", "return_code")
    if flags[3]:
        keys += ("tau", "tau_err", "alpha", "alpha_err", "nu_tau")
    for i in range(n):  # padded lanes are discarded
        results[bucket.owners[i]] = {k: out[k][i] for k in keys}
    bucket.ports.clear(); bucket.noise.clear(); bucket.masks.clear()
    bucket.Ps.clear(); bucket.nu_fits.clear(); bucket.theta0.clear()
    bucket.owners.clear()
    return dt, resolved


def _assemble_archive(m, results, modelfile, fit_DM, bary,
                      addtnl_toa_flags, log10_tau=False,
                      alpha_fitted=False):
    """Build the TOA objects + DeltaDM stats for one archive from the
    scattered fit results."""
    toas, dDMs, dDM_errs = [], [], []
    for j, isub in enumerate(m.ok):
        r = results.get((m.iarch, int(isub)))
        if r is None:
            continue
        P = m.Ps[j]
        phi = float(r["phi"])
        toa_mjd = m.epochs[j].add_seconds(phi * P + m.backend_delay)
        df = m.dfs[j] if bary else 1.0
        DM_j = float(r["DM"]) * (df if (bary and fit_DM) else 1.0)
        flags = {}
        if "tau" in r:
            # same flag set as GetTOAs (scat_time in us, Doppler-
            # corrected like the wideband pipeline)
            flags.update(scat_time_flags(
                float(r["tau"]), float(r["tau_err"]), P / df, log10_tau))
            flags["scat_ref_freq"] = float(r["nu_tau"]) * df
            flags["scat_ind"] = float(r["alpha"])
            if alpha_fitted:
                flags["scat_ind_err"] = float(r["alpha_err"])
        flags.update({
            "be": m.backend, "fe": m.frontend,
            "f": f"{m.frontend}_{m.backend}",
            "nbin": int(m.nbin), "nch": int(m.nchan),
            "subint": int(isub), "tobs": m.subtimes[j],
            "tmplt": str(modelfile), "snr": float(r["snr"]),
            "gof": float(r["chi2"] / max(float(r["dof"]), 1.0)),
        })
        flags.update(addtnl_toa_flags)
        DM_out = DM_j if fit_DM else None
        DM_err_out = float(r["DM_err"]) if fit_DM else None
        toas.append(TOA(
            m.datafile, float(r["nu_DM"]), toa_mjd,
            float(r["phi_err"]) * P * 1e6, m.telescope,
            m.telescope_code, DM_out, DM_err_out, flags))
        if fit_DM:
            dDMs.append(DM_j - m.DM0_arch)
            dDM_errs.append(DM_err_out)
    mean, err = delta_dm_stats(dDMs, dDM_errs)
    return toas, mean, err


def stream_wideband_TOAs(datafiles, modelfile, nsub_batch=256,
                         fit_DM=True, nu_ref_DM=None, DM0=None, bary=True,
                         tscrunch=False, fit_scat=False, log10_tau=True,
                         scat_guess=None, fix_alpha=False, max_iter=25,
                         prefetch=True, addtnl_toa_flags={}, tim_out=None,
                         quiet=False):
    """Measure wideband (phi[, DM[, tau, alpha]]) TOAs for many
    archives with cross-archive batched dispatches.

    fit_scat/log10_tau/scat_guess/fix_alpha follow GetTOAs.get_TOAs
    (scat_guess may be (tau_s, nu, alpha), "auto" for the data-driven
    seed, or None for the neutral half-bin); scattering buckets run the
    complex engine, no-scattering buckets keep the fast path.

    tim_out: optional .tim path; each archive's TOA lines are APPENDED
    as soon as all its subints are fitted, so a campaign interrupted
    mid-run keeps every completed archive's results on disk (the
    fault-tolerance analogue of the reference's write-the-model-every-
    iteration habit, ppgauss.py:208-212).

    Returns a DataBunch with:
      TOA_list        — TOA objects in archive order
      order           — archive paths measured
      DM0s            — per-archive nominal DM (offset-DM reference)
      DeltaDM_means / DeltaDM_errs — per-archive offset-DM statistics
      fit_duration    — total seconds spent in fit dispatches
      nfit            — number of fused dispatches fired
    """
    if isinstance(datafiles, str):
        datafiles = (_read_metafile(datafiles) if _is_metafile(datafiles)
                     else [datafiles])
    else:
        datafiles = list(datafiles)
    scat_guess = _validate_scat_guess(scat_guess, fit_scat)
    if not fit_scat:
        log10_tau = False
    model = TemplateModel(modelfile, quiet=quiet)
    # scattering baked into the template makes the portrait depend on
    # the folding period (tau seconds -> bins) — such templates must
    # not be shared across archives with different P
    p_dependent = model.has_scattering()
    if tim_out:
        # fresh checkpoint file: a rerun must not append onto a
        # previous campaign's lines
        open(tim_out, "w").close()

    def _loader(f):
        return load_for_toas(f, tscrunch=tscrunch, quiet=True)

    buckets = {}
    results = {}
    meta = []        # minimal per-archive record for TOA assembly
    meta_by_iarch = {}
    remaining = {}   # iarch -> subints not yet fitted
    assembled = {}   # iarch -> (toas, DeltaDM_mean, DeltaDM_err)
    fit_duration = 0.0
    nfit = 0
    t_start = time.time()

    def do_flush(b):
        nonlocal fit_duration, nfit
        dt, resolved = _flush(b, nu_ref_DM, max_iter, nsub_batch, results,
                              log10_tau=log10_tau)
        fit_duration += dt
        nfit += 1
        touched = set()
        for iarch, _ in resolved:
            remaining[iarch] -= 1
            touched.add(iarch)
        for ia in touched:
            # emit completed archives immediately: an interrupted
            # campaign keeps everything finished so far
            if remaining[ia] == 0 and ia not in assembled:
                m = meta_by_iarch[ia]
                out = _assemble_archive(
                    m, results, modelfile, fit_DM, bary,
                    addtnl_toa_flags, log10_tau=log10_tau,
                    alpha_fitted=fit_scat and not fix_alpha)
                assembled[ia] = out
                # the per-subint records are folded into the assembly;
                # dropping them keeps host memory O(bucket)
                for isub in m.ok:
                    results.pop((ia, int(isub)), None)
                if tim_out:
                    write_TOAs(out[0], outfile=tim_out, append=True)

    for iarch, (datafile, d) in enumerate(
            _iter_archives(datafiles, _loader, prefetch)):
        if isinstance(d, Exception):
            print(f"Skipping {datafile}: {d}")
            continue
        ok = np.asarray(d.ok_isubs, int)
        if d.nsub == 0 or len(ok) == 0:
            print(f"No subints to fit in {datafile}; skipping.")
            continue
        nchan, nbin = d.nchan, d.nbin
        freqs0 = np.asarray(d.freqs[0], float)
        P_mean = float(np.mean(d.Ps[ok]))
        try:
            modelx = model.portrait(freqs0, nbin, P=P_mean)
        except ValueError as e:
            print(f"Skipping {datafile}: {e}")
            continue
        base_key = (nchan, nbin, freqs0.tobytes())
        if p_dependent:
            base_key += (round(P_mean, 12),)

        DM_stored = float(d.DM)
        DM0_arch = DM_stored if DM0 is None else float(DM0)
        DM_guess = DM_stored if DM_stored != 0.0 else DM0_arch
        masks = np.asarray(d.weights[ok] > 0.0, float)
        noise = np.asarray(d.noise_stds[ok, 0], float)
        snrs_chan = np.asarray(d.SNRs[ok, 0], float) * masks
        nu_fit_arr = snr_weighted_nu_fit(snrs_chan, freqs0)

        # keep only what TOA assembly needs — NOT the data cube
        m = DataBunch(
            datafile=datafile, iarch=iarch, ok=ok,
            DM0_arch=DM0_arch, nbin=nbin, nchan=nchan,
            epochs=[d.epochs[isub] for isub in ok],
            Ps=[float(d.Ps[isub]) for isub in ok],
            dfs=[float(d.doppler_factors[isub]) for isub in ok],
            subtimes=[float(d.subtimes[isub]) for isub in ok],
            backend_delay=d.backend_delay, backend=d.backend,
            frontend=d.frontend, telescope=d.telescope,
            telescope_code=d.telescope_code)
        meta.append(m)
        meta_by_iarch[iarch] = m
        remaining[iarch] = len(ok)
        ports = np.asarray(d.subints[ok, 0], float)
        nchx = masks.sum(axis=1).astype(int)

        # tau/alpha seeds (mirrors GetTOAs.get_TOAs)
        alpha0 = (model.gauss.alpha if model.is_gaussian
                  else scattering_alpha)
        if scat_guess is not None and not isinstance(scat_guess, str):
            t_s, nu_s, a_s = scat_guess
            tau0 = (t_s / P_mean) * (nu_fit_arr / nu_s) ** a_s
            alpha0 = a_s
        elif fit_scat and scat_guess == "auto":
            from ..fit.portrait import estimate_tau_batch

            tau0 = np.asarray(estimate_tau_batch(
                jnp.asarray(ports, jnp.float32),
                jnp.asarray(modelx, jnp.float32),
                jnp.asarray(noise, jnp.float32),
                jnp.asarray(masks, jnp.float32)))
        elif fit_scat:
            tau0 = np.full(len(ok), 0.5 / nbin)
        else:
            tau0 = np.zeros(len(ok))

        base_flags = (True, bool(fit_DM), False, bool(fit_scat),
                      bool(fit_scat and not fix_alpha))
        for j, isub in enumerate(ok):
            # degenerate geometry: 1 usable channel -> phase-only
            eff_flags = ((True, False, False, False, False)
                         if nchx[j] <= 1 else base_flags)
            key = base_key + (eff_flags,)
            if key not in buckets:
                buckets[key] = _Bucket(freqs0, nbin, modelx, eff_flags)
            b = buckets[key]
            th = np.zeros(5)
            th[1] = DM_guess
            th[3] = (np.log10(max(tau0[j], 1e-12)) if log10_tau
                     else tau0[j])
            th[4] = alpha0
            b.ports.append(ports[j])
            b.noise.append(noise[j])
            b.masks.append(masks[j])
            b.Ps.append(float(d.Ps[isub]))
            b.nu_fits.append(float(nu_fit_arr[j]))
            b.theta0.append(th)
            b.owners.append((iarch, int(isub)))
            if len(b) >= nsub_batch:
                do_flush(b)

    for b in buckets.values():
        if len(b):
            do_flush(b)

    # ---- collect TOAs + per-archive DeltaDM stats in archive order --
    TOA_list = []
    order, DM0s, DeltaDM_means, DeltaDM_errs = [], [], [], []
    for m in meta:
        toas, mean, err = assembled.get(m.iarch) or _assemble_archive(
            m, results, modelfile, fit_DM, bary, addtnl_toa_flags,
            log10_tau=log10_tau, alpha_fitted=fit_scat and not fix_alpha)
        TOA_list.extend(toas)
        order.append(m.datafile)
        DM0s.append(m.DM0_arch)
        DeltaDM_means.append(mean)
        DeltaDM_errs.append(err)

    if not quiet:
        tot = time.time() - t_start
        n = len(TOA_list)
        print(f"streamed {n} TOAs from {len(order)} archives in "
              f"{tot:.2f} s ({nfit} fused dispatches, "
              f"{fit_duration:.2f} s fitting, "
              f"{n / max(tot, 1e-9):.1f} TOAs/s end-to-end)")
    return DataBunch(TOA_list=TOA_list, order=order, DM0s=DM0s,
                     DeltaDM_means=DeltaDM_means,
                     DeltaDM_errs=DeltaDM_errs,
                     fit_duration=fit_duration, nfit=nfit)
