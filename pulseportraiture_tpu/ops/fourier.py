"""Real-valued DFT as MXU matmuls.

XLA's native FFT lowering on TPU is catastrophically slow for this
workload's shapes (measured ~20 s for a (128, 512, 2048) rfft on
v5e-1 — three orders of magnitude off), and complex types cannot
coexist with Pallas kernels under the tunneled runtime.  Both
problems disappear by expressing the length-n real DFT as two real
matmuls against precomputed cos/sin matrices: for nbin <= a few
thousand the (n, nharm) weight matrices are small (16 MB at n=2048),
live in HBM once per shape, and the contraction runs on the MXU at
full throughput.

API is split-real throughout: rfft_mm(x) -> (Xr, Xi),
irfft_mm(Xr, Xi, n) -> x.  Matches numpy's rfft/irfft conventions
(tests/test_ops.py asserts parity with jnp.fft on CPU).
"""

from functools import lru_cache

import jax
import jax.numpy as jnp
import numpy as np

from .. import config

__all__ = ["rfft_mm", "irfft_mm", "rfft_c", "irfft_c", "rfft_sr",
           "irfft_sr", "use_matmul_dft", "use_dft_fold"]


def _default_precision():
    """Matmul precision from config.dft_precision
    ('highest' | 'high' | 'default').

    'highest'/'high' keep f32-grade accuracy (~1e-7/1e-6 relative).
    'default' is single-pass bf16 — ~3x faster on the MXU but ~1e-3
    relative DFT error; only safe where the consumer has validated the
    end-to-end accuracy gate at that setting (see bench.py)."""
    name = str(getattr(config, "dft_precision", "highest")).lower()
    if name not in ("highest", "high", "default"):
        raise ValueError(
            f"config.dft_precision must be 'highest', 'high' or "
            f"'default', got {name!r}")
    return getattr(jax.lax.Precision, name.upper())


# weight caches hold HOST numpy arrays: a jnp array materialized during
# a jit trace is a tracer, and caching one leaks it across traces
@lru_cache(maxsize=None)
def _rfft_weights(n, dtype_str, nharm=None):
    """(Wc, Ws): x @ Wc = Re rfft(x), x @ Ws = Im rfft(x).

    nharm truncates the output to the first nharm harmonics (a
    band-limited DFT: exact for any consumer that only reads k <
    nharm, at nharm/(n/2+1) of the matmul cost — the fit's harmonic
    window, fit/portrait.model_harmonic_window)."""
    k = np.arange(n // 2 + 1 if nharm is None else nharm)
    j = np.arange(n)
    ang = 2.0 * np.pi * np.outer(j, k) / n
    Wc = np.cos(ang)
    Ws = -np.sin(ang)
    return (Wc.astype(dtype_str), Ws.astype(dtype_str))


@lru_cache(maxsize=None)
def _irfft_weights(nharm, n, dtype_str):
    """(Vc, Vs): Xr @ Vc + Xi @ Vs = irfft(X, n).

    Hermitian-symmetry weighting: interior harmonics count twice, the
    DC and (for even n) Nyquist rows once.
    """
    k = np.arange(nharm)
    j = np.arange(n)
    ang = 2.0 * np.pi * np.outer(k, j) / n
    wk = np.full(nharm, 2.0)
    wk[0] = 1.0
    if n % 2 == 0 and nharm == n // 2 + 1:
        wk[-1] = 1.0
    Vc = (wk[:, None] * np.cos(ang)) / n
    Vs = (-wk[:, None] * np.sin(ang)) / n
    return (Vc.astype(dtype_str), Vs.astype(dtype_str))


@lru_cache(maxsize=None)
def _rfft_fold_weights(n, dtype_str, nharm=None):
    """Half-length weights for the fold-symmetry real DFT (even n):
      Re X_k = x[0] + (-1)^k x[n/2] + sum_{j=1}^{n/2-1} xe_j cos(2pi jk/n)
      Im X_k =                      - sum_{j=1}^{n/2-1} xo_j sin(2pi jk/n)
    with xe_j = x[j] + x[n-j], xo_j = x[j] - x[n-j] — two (n/2-1)-row
    matmuls instead of two n-row ones (exactly half the MACs, f32-grade
    accuracy; see config.dft_fold for where this wins)."""
    j = np.arange(1, n // 2)
    k = np.arange(n // 2 + 1 if nharm is None else nharm)
    ang = 2.0 * np.pi * np.outer(j, k) / n
    sgn = (-1.0) ** k
    return (np.cos(ang).astype(dtype_str), (-np.sin(ang)).astype(dtype_str),
            sgn.astype(dtype_str))


def use_dft_fold():
    """Whether rfft_mm should take the fold-symmetry half-length path:
    config.dft_fold (True/False force; 'auto' = non-TPU backends, where
    the halved sgemm FLOPs win — on TPU v5e the lane-reversal relayout
    measured a net loss, benchmarks/exp_folddft.py).  Read at trace
    time.  The default is False: folding re-associates the DFT sums, so
    lanes that guarantee bit-stable output (the raw-campaign bucket
    program) keep the direct matmul unless the deployment opts in."""
    from ..tune.capability import resolve_auto

    setting = getattr(config, "dft_fold", False)
    return resolve_auto("dft_fold", setting, label="config.dft_fold")


def rfft_mm(x, precision=None, nharm=None, fold=None):
    """Real DFT of the last axis via matmul: (..., n) -> two (..., nharm)
    real arrays (Re, Im); nharm defaults to the full n//2+1.  precision
    None -> config.dft_precision ('highest' keeps f32 accuracy at the
    1e-7 level; 'high' ~1e-6 and ~20% faster end-to-end; bf16
    single-pass would cost ~1e-3).  fold None -> config.dft_fold (the
    half-length fold-symmetry contraction; False forces the direct
    matmul for callers that must stay bit-stable)."""
    if precision is None:
        precision = _default_precision()
    n = x.shape[-1]
    if fold is None:
        fold = use_dft_fold()
    if fold and n % 2 == 0 and n >= 8:
        Wc_h, Ws_h, sgn = _rfft_fold_weights(n, str(x.dtype), nharm)
        head = x[..., 1:n // 2]
        tail = jnp.flip(x[..., n // 2 + 1:], axis=-1)
        dr = (jnp.matmul(head + tail, Wc_h, precision=precision)
              + x[..., 0:1] + x[..., n // 2:n // 2 + 1] * sgn)
        di = jnp.matmul(head - tail, Ws_h, precision=precision)
        return dr, di
    Wc, Ws = _rfft_weights(n, str(x.dtype), nharm)
    return (
        jnp.matmul(x, Wc, precision=precision),
        jnp.matmul(x, Ws, precision=precision),
    )


def irfft_mm(Xr, Xi, n=None, precision=None):
    """Inverse of rfft_mm: two (..., nharm) real arrays -> (..., n)."""
    if precision is None:
        precision = _default_precision()
    nharm = Xr.shape[-1]
    if n is None:
        n = 2 * (nharm - 1)
    Vc, Vs = _irfft_weights(nharm, n, str(Xr.dtype))
    return (
        jnp.matmul(Xr, Vc, precision=precision)
        + jnp.matmul(Xi, Vs, precision=precision)
    )


def use_matmul_dft():
    """Whether complex-interface DFTs should route through the matmul
    weights: config.use_matmul_dft (True/False force; 'auto' = TPU
    backends, where XLA's native FFT lowering is ~2000x slower at this
    workload's shapes).  Read at trace time."""
    from ..tune.capability import resolve_auto

    setting = getattr(config, "use_matmul_dft", "auto")
    # strict like _default_precision: a typo ('true', 'ture', ...)
    # must not silently mean 'auto' — resolve_auto enforces it
    return resolve_auto("use_matmul_dft", setting,
                        label="config.use_matmul_dft")


def rfft_c(x, precision=None):
    """numpy-convention rfft of the last axis returning a COMPLEX array,
    backend-dispatched: matmul DFT on TPU (complex arithmetic compiles
    fine there — only the FFT lowering and Pallas/complex mixing are
    broken), jnp.fft.rfft elsewhere.  Use this instead of jnp.fft.rfft
    in any code that must run on the accelerator (fit engines, rotation
    kernels); offline host-pinned paths may keep jnp.fft.

    f64 inputs always take the jnp.fft path: the matmul route would
    produce complex128, which TPU rejects outright — whereas XLA's FFT
    handles the f64-under-x64 host-side paths the pipelines run.
    bf16 inputs upcast to f32 first (lax.complex has no bf16).

    Unlike rfft_mm, the complex interface clamps config.dft_precision
    'default' up to 'high': its consumers (rotation/alignment kernels,
    scattering convolutions, CCF searches) have no end-to-end accuracy
    gate, so the single-pass-bf16 setting — validated only for the
    portrait fit — must not silently degrade them."""
    x = jnp.asarray(x)
    if use_matmul_dft() and x.dtype in (jnp.float32, jnp.bfloat16):
        if x.dtype == jnp.bfloat16:
            x = x.astype(jnp.float32)
        Xr, Xi = rfft_mm(x, precision=_gated_precision(precision))
        return jax.lax.complex(Xr, Xi)
    return jnp.fft.rfft(x, axis=-1)


def irfft_c(X, n=None, precision=None):
    """Inverse of rfft_c: complex (..., nharm) -> real (..., n)."""
    X = jnp.asarray(X)
    if use_matmul_dft() and X.dtype == jnp.complex64:
        return irfft_mm(jnp.real(X), jnp.imag(X), n=n,
                        precision=_gated_precision(precision))
    return jnp.fft.irfft(X, n=n, axis=-1)


def rfft_sr(x, precision=None):
    """Split-real backend-dispatched rfft: (..., n) -> (Re, Im), each
    (..., n//2+1) real.  The split-real analogue of rfft_c: matmul-DFT
    weights where use_matmul_dft() says so (TPU, where XLA's FFT
    lowering is unusable AND complex dtypes cannot appear in the
    program at all), jnp.fft elsewhere (CPU f64 matmul DFTs would cost
    ~n/log n times the FFT's FLOPs).  For jitted programs that must
    stay complex-free on the accelerator end to end (the device align
    accumulate, parallel/batch.py) — the jnp.fft arm materializes a
    complex intermediate INSIDE the program, which is fine on backends
    that take that arm.  Precision gating follows the complex
    interface (config 'default' clamps to 'high')."""
    x = jnp.asarray(x)
    if use_matmul_dft():
        return rfft_mm(x, precision=_gated_precision(precision),
                       fold=False)
    X = jnp.fft.rfft(x, axis=-1)
    return jnp.real(X), jnp.imag(X)


def irfft_sr(Xr, Xi, n=None, precision=None):
    """Inverse of rfft_sr: (Re, Im) -> (..., n) real, same dispatch."""
    if use_matmul_dft():
        return irfft_mm(Xr, Xi, n=n,
                        precision=_gated_precision(precision))
    return jnp.fft.irfft(jax.lax.complex(Xr, Xi), n=n, axis=-1)


def _gated_precision(precision):
    """Explicit precision wins; otherwise config.dft_precision with
    'default' clamped to 'high' (see rfft_c docstring)."""
    if precision is not None:
        return precision
    p = _default_precision()
    if p == jax.lax.Precision.DEFAULT:
        return jax.lax.Precision.HIGH
    return p
