"""Fold-symmetry rFFT experiment (round 4): the direct matmul DFT is
MXU-FLOP-bound (X assembly fuses into its epilogue; 31 ms at 640x512x
2048 'default'), and cos/sin symmetry of real input halves the FLOPs
exactly: with xe[j] = x[j] + x[n-j], xo[j] = x[j] - x[n-j] (j in
[1, n/2)),

  Re X_k = x[0] + (-1)^k x[n/2] + sum_j xe[j] cos(2 pi j k / n)
  Im X_k = -sum_j xo[j] sin(2 pi j k / n)

two (n/2-1)-contraction matmuls replace two n-contraction ones.  Also
probes output-width padding (1025 is 8*128+1 — ragged) and a concat
[Wc|Ws] single-matmul variant.
"""

import json
import sys

sys.path.insert(0, __file__.rsplit("/", 2)[0])


def main():
    import jax
    import jax.numpy as jnp
    import numpy as np

    import pulseportraiture_tpu  # noqa: F401
    from pulseportraiture_tpu import config

    config.dft_precision = "default"

    from benchmarks.common import devtime
    from pulseportraiture_tpu.ops.fourier import rfft_mm

    NB, NCHAN, NBIN = 640, 512, 2048
    NHARM = NBIN // 2 + 1
    DT = jnp.float32

    ports = jax.block_until_ready(jax.jit(
        lambda k: jax.random.normal(k, (NB, NCHAN, NBIN), DT))(
            jax.random.PRNGKey(0)))
    model = jax.block_until_ready(jax.jit(
        lambda k: jax.random.normal(k, (NCHAN, NBIN), DT))(
            jax.random.PRNGKey(1)))

    mr, mi = rfft_mm(model, precision="highest")
    mr = jax.block_until_ready(mr)

    def assemble(dr, di):
        Xr = (dr * mr + di * mi).astype(jnp.bfloat16)
        Xi = (di * mr - dr * mi).astype(jnp.bfloat16)
        Sd = jnp.sum(dr**2 + di**2, axis=(-1, -2))
        return Xr, Xi, Sd

    def direct(p, s):
        dr, di = rfft_mm(p * (1.0 + s))
        return assemble(dr, di)

    # direct with padded output width (1152 = 9*128): ragged-tile probe
    j = np.arange(NBIN)
    kpad = np.arange(1152)
    angp = 2.0 * np.pi * np.outer(j, kpad) / NBIN
    Wcp = jnp.asarray(np.cos(angp), DT)
    Wsp = jnp.asarray(-np.sin(angp), DT)

    def direct_pad(p, s):
        x = p * (1.0 + s)
        dr = jnp.matmul(x, Wcp)[..., :NHARM]
        di = jnp.matmul(x, Wsp)[..., :NHARM]
        return assemble(dr, di)

    # concat single matmul [Wc | Ws] -> (n, 2*nharm)
    k = np.arange(NHARM)
    ang = 2.0 * np.pi * np.outer(j, k) / NBIN
    Wcat = jnp.asarray(np.concatenate(
        [np.cos(ang), -np.sin(ang)], axis=1), DT)

    def direct_cat(p, s):
        x = p * (1.0 + s)
        o = jnp.matmul(x, Wcat)
        return assemble(o[..., :NHARM], o[..., NHARM:])

    # fold: half-length DCT/DST
    jh = np.arange(1, NBIN // 2)           # (1023,)
    angh = 2.0 * np.pi * np.outer(jh, k) / NBIN
    Wc_h = jnp.asarray(np.cos(angh), DT)   # (1023, 1025)
    Ws_h = jnp.asarray(-np.sin(angh), DT)
    sgn = jnp.asarray((-1.0) ** k, DT)     # (1025,)

    def fold(p, s):
        x = p * (1.0 + s)
        xr = jnp.flip(x[..., 1:], axis=-1)  # x[n-j], j=1..n-1 reversed
        head = x[..., 1:NBIN // 2]
        tail = xr[..., :NBIN // 2 - 1]      # x[n-j] for j=1..1023
        xe = head + tail
        xo = head - tail
        dr = (jnp.matmul(xe, Wc_h)
              + x[..., 0:1] + x[..., NBIN // 2:NBIN // 2 + 1] * sgn)
        di = jnp.matmul(xo, Ws_h)
        return assemble(dr, di)

    # fold with concat single matmul
    Wcat_h = jnp.concatenate([Wc_h, Ws_h], axis=1)  # (1023, 2050)

    def fold_cat(p, s):
        x = p * (1.0 + s)
        xr = jnp.flip(x[..., 1:], axis=-1)
        head = x[..., 1:NBIN // 2]
        tail = xr[..., :NBIN // 2 - 1]
        xeo = jnp.concatenate([head + tail, head - tail], axis=-2)
        o = jnp.matmul(xeo, Wcat_h)
        ne = head.shape[-2]
        dr = (o[..., :ne, :NHARM]
              + x[..., 0:1] + x[..., NBIN // 2:NBIN // 2 + 1] * sgn)
        di = o[..., ne:, NHARM:]
        return dr, di  # shapes differ; skip assemble fairness here

    # --- accuracy vs f64 oracle -------------------------------------
    ph = np.asarray(ports[:1]).astype(np.float64)
    F64 = np.fft.rfft(ph, axis=-1)[0]
    scale = np.abs(F64).max()

    def acc(fn):
        Xr, Xi, _ = jax.jit(fn)(ports[:1], jnp.float32(0.0))
        # recover dFT-level error via the oracle-assembled comparison:
        # compare X = d * conj(m) both ways
        m64 = (np.asarray(mr) + 1j * np.asarray(mi)).astype(complex)
        X64 = F64 * np.conj(m64)
        Xc = (np.asarray(Xr, np.float64) + 1j * np.asarray(Xi))[0]
        return float(np.abs(Xc - X64).max() / np.abs(X64).max())

    jobs = [("direct", direct), ("direct_pad1152", direct_pad),
            ("direct_cat", direct_cat), ("fold", fold)]

    counter = [0]
    for name, fn in jobs:
        err = acc(fn)
        jfn = jax.jit(fn)

        def call(jfn=jfn):
            counter[0] += 1
            return jfn(ports, jnp.float32(counter[0] * 1e-7))

        slope, single = devtime(
            call, lambda r: (r[0].astype(jnp.float32).sum()
                             + r[1].astype(jnp.float32).sum()
                             + r[2].sum()), K=6, warm=2)
        print(json.dumps({"variant": name,
                          "slope_ms": round(slope * 1e3, 2),
                          "single_ms": round(single * 1e3, 1),
                          "max_rel_err": f"{err:.2e}"}), flush=True)


if __name__ == "__main__":
    main()
