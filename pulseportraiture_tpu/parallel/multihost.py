"""Multi-host (multi-process) scale-out over DCN.

The workload's distributed structure (SURVEY.md §2.9): per-(archive,
subint) fits are independent, so the campaign axis parallelizes across
HOSTS with no inter-host communication at all — each process measures
its own archive shard and only small TOA summaries ever cross the
data-center network.  Within a host/slice, the ('data', 'chan') mesh
of parallel/mesh.py handles the chips (ICI); a GLOBAL mesh over all
processes' devices is only needed when one enormous fit must span
hosts (possible — the chi^2 reduction becomes a psum over DCN — but
never required at realistic portrait sizes).

Recipe (one process per host, standard JAX distributed bootstrap):

    from pulseportraiture_tpu import parallel
    parallel.init_multihost(coordinator_address="host0:1234",
                            num_processes=N, process_id=i)
    mine = parallel.shard_files(datafiles)         # this host's slice
    res = stream_wideband_TOAs(mine, model, tim_out=f"part{i}.tim")
    # .tim parts concatenate; or gather summaries in-process (returns
    # one array per process; ragged shard lengths are handled):
    per_host_dms = parallel.process_allgather(res.DeltaDM_means)

Everything degrades to a no-op single-process path, which is how the
test suite exercises it (the driver's dryrun and the 8-virtual-device
tests cover the intra-host mesh; true multi-host needs real hosts).
"""

import jax
import numpy as np

from .mesh import make_mesh

__all__ = ["init_multihost", "process_count", "process_index",
           "shard_files", "global_mesh", "process_allgather"]


def _cluster_env_detected():
    """Whether jax's cluster auto-detection would find a distributed
    environment (SLURM, GCE TPU pods, the JAX_COORDINATOR_ADDRESS env
    family): True / False when the registry is inspectable, None when
    the private API moved (callers then fall back to probing
    jax.distributed.initialize itself).

    PRIVATE-API PIN: jax._src.clusters.ClusterEnv._cluster_types is
    private and verified against jax 0.9.x; tests/test_parallel.py::
    test_cluster_env_private_api_is_inspectable is the canary that
    makes a jax upgrade moving it FAIL VISIBLY instead of silently
    degrading detection to the probe fallback (VERDICT r3 weak #4)."""
    try:
        from jax._src.clusters import ClusterEnv

        return any(c.is_env_present() for c in ClusterEnv._cluster_types)
    except Exception:
        return None


def init_multihost(coordinator_address=None, num_processes=None,
                   process_id=None, **kwargs):
    """Initialize JAX's distributed runtime (multi-host).

    With explicit arguments, failures raise.  With none, defer to
    JAX's own cluster auto-detection (SLURM, GCE TPU pods, the
    JAX_COORDINATOR_ADDRESS env family): if a cluster is detected the
    runtime initializes and True is returned; on a plain single
    machine the detection error is swallowed and False is returned, so
    the single-process path stays safe on laptops and CI."""
    if (coordinator_address is None and num_processes is None
            and process_id is None and not kwargs):
        detected = _cluster_env_detected()
        if detected is False:
            # structural signal: no cluster environment present — skip
            # the bootstrap entirely instead of catching its error.
            # A DETECTED cluster whose bootstrap fails (unreachable
            # coordinator, double initialization) surfaces below: a
            # swallowed error would make every task run the full
            # campaign as process 0 of 1.
            return False
        _enable_cpu_collectives()
        try:
            jax.distributed.initialize()
            return True
        except ValueError as e:
            # detection result unknown (private jax API unavailable):
            # fall back to the no-cluster error jax raises on a plain
            # machine — ValueError("coordinator_address should be
            # defined.").  Real bootstrap failures are RuntimeError.
            if detected is None and "coordinator_address" in str(e):
                return False
            raise
    _enable_cpu_collectives()
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes, process_id=process_id, **kwargs)
    return True


def _enable_cpu_collectives():
    """Multi-process CPU backends need a cross-process collectives
    implementation (gloo) configured BEFORE the client is created —
    without it every process builds an isolated 1-process client and
    jax.process_count() silently stays 1.  No-op for TPU backends
    (their ICI/DCN collectives are built in)."""
    try:
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except (AttributeError, KeyError, ValueError):
        # config key moved/renamed in a future jax: TPU pods are
        # unaffected; CPU multi-process then needs the caller to set
        # the equivalent knob
        pass


def process_count():
    return jax.process_count()


def process_index():
    return jax.process_index()


def shard_files(datafiles, index=None, count=None):
    """This process's round-robin slice of a campaign file list.

    Round-robin (not contiguous blocks) so heterogeneous archive sizes
    balance across hosts without knowing them in advance."""
    index = jax.process_index() if index is None else int(index)
    count = jax.process_count() if count is None else int(count)
    return list(datafiles)[index::count]


def global_mesh(n_chan=1):
    """A ('data', 'chan') mesh over ALL processes' devices (DCN+ICI).
    Under a single process this is exactly make_mesh().  Sharding a
    single fit's channel axis across hosts turns the chi^2 reduction
    into a psum over DCN — legal, but prefer host-sharded campaigns
    (shard_files) whenever fits fit on one host."""
    return make_mesh(n_chan=n_chan, devices=list(jax.devices()))


def process_allgather(x):
    """Gather a small per-process 1-D array to every process (host
    numpy in; returns a LIST of per-process arrays, which may have
    different lengths — round-robin campaign shards are ragged
    whenever the process count does not divide the file count, and the
    underlying collective needs uniform shapes, so lengths are
    exchanged first and the payload NaN-padded to the max).
    Single-process: [x]."""
    x = np.atleast_1d(np.asarray(x, np.float64))
    if jax.process_count() == 1:
        return [x]
    from jax.experimental import multihost_utils

    lens = np.asarray(multihost_utils.process_allgather(
        np.asarray(len(x), np.int64)))
    n_max = int(lens.max())
    pad = np.full(n_max, np.nan)
    pad[: len(x)] = x
    g = np.asarray(multihost_utils.process_allgather(pad))
    return [g[i, : int(lens[i])] for i in range(len(lens))]
