"""BASELINE.md config 3: full (phi, DM, GM, tau, alpha) scattering fit,
64 subints x 512 chan x 2048 bin, jitted inner optimizer, one TPU chip.

Default engine is the round-3 complex-free fast lane
(fit_portrait_batch_fast -> fast_scatter_fit_one): matmul DFTs + the
fused analytic _cgh_scatter Newton loop in one real-arithmetic program.
`--engine complex` benches the round-2 complex engine for comparison;
`--compensated` turns on the Dot2 reductions.

Prints ONE JSON line like bench.py.
"""

import json
import sys

import numpy as np

sys.path.insert(0, __file__.rsplit("/", 2)[0])


def main():
    import jax
    import jax.numpy as jnp

    import pulseportraiture_tpu  # noqa: F401
    from pulseportraiture_tpu import config
    config.dft_precision = "default"
    engine = "complex" if "--engine=complex" in sys.argv[1:] or \
        ("--engine" in sys.argv[1:] and "complex" in sys.argv[1:]) \
        else "fast"
    if "--compensated" in sys.argv[1:]:
        config.scatter_compensated = True

    from benchmarks.common import bench_model, devtime
    from pulseportraiture_tpu.fit import FitFlags, fit_portrait_batch
    from pulseportraiture_tpu.fit.portrait import fit_portrait_batch_fast
    from pulseportraiture_tpu.ops.fourier import irfft_c, rfft_c
    from pulseportraiture_tpu.ops.scattering import (scattering_portrait_FT,
                                                     scattering_times)

    NB, NCHAN, NBIN = 64, 512, 2048
    DT = jnp.float32
    P, NU_FIT = 0.003, 1500.0
    TAU_S = 2e-4
    model, freqs = bench_model(NCHAN, NBIN)

    @jax.jit
    def synth(key):
        taus = scattering_times(TAU_S / P, -4.0, freqs, NU_FIT).astype(DT)
        B = scattering_portrait_FT(taus, NBIN // 2 + 1)
        sFT = rfft_c(model) * B
        k1, k2 = jax.random.split(key)
        phis = 0.05 * jax.random.uniform(k1, (NB,), DT)
        kk = jnp.arange(sFT.shape[-1], dtype=DT)
        ph = jnp.exp(-2j * jnp.pi * phis[:, None, None] * kk)
        rot = irfft_c(sFT * ph, n=NBIN)
        return rot + 0.03 * jax.random.normal(k2, rot.shape, DT)

    ports = synth(jax.random.PRNGKey(0))
    noise = jnp.full((NB, NCHAN), 0.03, DT)
    models = model  # shared 2-D template: one model DFT for the batch
    # data-driven tau seed (fit.portrait.estimate_tau_batch) — the
    # pipeline's scat_guess="auto"; cuts Newton evals severalfold vs
    # the neutral half-bin seed
    from pulseportraiture_tpu.fit.portrait import estimate_tau_batch
    tau_seed = np.asarray(estimate_tau_batch(ports, model, noise))
    th0 = np.zeros((NB, 5), np.float32)
    th0[:, 3] = np.log10(np.maximum(tau_seed, 1e-12))
    th0[:, 4] = -4.0
    th0 = jnp.asarray(th0)

    flags = FitFlags(True, True, False, True, True)
    # harmonic window from the UNSCATTERED template's support (the
    # scattering kernel only narrows the spectrum; production templates
    # are host numpy so pipelines derive this automatically)
    from pulseportraiture_tpu.fit.portrait import model_harmonic_window
    hwin = model_harmonic_window(np.asarray(model), NBIN)

    def run():
        if engine == "fast":
            return fit_portrait_batch_fast(
                ports, models, noise, freqs, P, NU_FIT,
                fit_flags=flags, theta0=th0, log10_tau=True, max_iter=40,
                harmonic_window=hwin if hwin is not None else False)
        return fit_portrait_batch(
            ports, models, noise, freqs, P, NU_FIT,
            fit_flags=flags, theta0=th0, log10_tau=True, max_iter=40)

    r = run()
    exp = (TAU_S / P) * (np.asarray(r.nu_tau) / NU_FIT) ** np.asarray(r.alpha)
    rel = np.abs(np.asarray(r.tau) - exp) / exp
    slope, single = devtime(run, lambda rr: rr.phi)
    print(json.dumps({
        "metric": "5-param scattering fits, 64sub x 512ch x 2048bin",
        "value": round(NB / slope, 2),
        "unit": "TOAs/sec",
        "engine": engine,
        "compensated": bool(config.scatter_compensated),
        "batch_latency_ms": round(single * 1e3, 1),
        "device": str(jax.devices()[0]),
        "tau_rel_err_median": float(f"{np.median(rel):.3g}"),
        "nfev_median": float(np.median(np.asarray(r.nfeval))),
    }))


if __name__ == "__main__":
    main()
