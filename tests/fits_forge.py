"""A from-scratch PSRFITS *forge* for golden-file loader tests.

Deliberately shares NO code with pulseportraiture_tpu.io — every card,
table descriptor, and byte here is written by hand so that loader tests
built on it do not round-trip through the repo's own writer (the
closed-loop blind spot VERDICT round 2 flagged).  It also produces
layouts the repo's writer never emits: absent DAT_WTS/DAT_SCL/DAT_OFFS
columns, unsigned-byte / float32 DATA, alien TDIM spellings, ragged
per-subint DAT_FREQ, multi-row POLYCO tables, 4-pol Coherence data.

Only what the tests need is implemented; formats follow the FITS 4.0
standard directly (2880-byte blocks, 80-char cards, big-endian binary
tables).
"""

import numpy as np

BLOCK = 2880


def _card(key, value=None, comment=""):
    if value is None:
        s = key.ljust(8) + ("  " + comment if comment else "")
        return s[:80].ljust(80)
    if isinstance(value, bool):
        v = ("T" if value else "F").rjust(20)
    elif isinstance(value, (int, np.integer)):
        v = str(int(value)).rjust(20)
    elif isinstance(value, (float, np.floating)):
        v = f"{float(value):.14G}".rjust(20)
    else:
        v = ("'" + str(value).replace("'", "''").ljust(8) + "'").ljust(20)
    s = key.ljust(8) + "= " + v
    if comment:
        s += " / " + comment
    return s[:80].ljust(80)


def _header_bytes(cards):
    out = "".join(cards) + "END".ljust(80)
    pad = (-len(out)) % BLOCK
    return (out + " " * pad).encode("ascii")


def primary_hdu(extra_cards=()):
    cards = [_card("SIMPLE", True), _card("BITPIX", 8),
             _card("NAXIS", 0), _card("EXTEND", True)]
    cards += [_card(*c) for c in extra_cards]
    return _header_bytes(cards)


_CODE = {np.dtype("u1"): "B", np.dtype(">i2"): "I", np.dtype(">i4"): "J",
         np.dtype(">f4"): "E", np.dtype(">f8"): "D"}


def bintable_hdu(extname, columns, extra_cards=(), tdim_overrides=None,
                 col_cards=None):
    """columns: list of (name, big-endian ndarray shaped (nrows, ...)).
    tdim_overrides: {name: literal TDIM string} to test alien
    spellings; by default no TDIM card is written (readers must fall
    back to the header NCHAN/NPOL/NBIN geometry).
    col_cards: {name: {cardbase: value}} writes per-column indexed
    cards, e.g. {'DATA': {'TZERO': -128.0}} -> TZEROn (the FITS
    signed-byte convention)."""
    tdim_overrides = tdim_overrides or {}
    col_cards = col_cards or {}
    nrows = len(columns[0][1])
    cards = []
    fields = []
    stride = 0
    for i, (name, arr) in enumerate(columns, 1):
        arr = np.ascontiguousarray(arr)
        if arr.dtype.kind == "S":
            code = f"{arr.dtype.itemsize}A"
            nel = 1
            width = arr.dtype.itemsize
        else:
            be = arr.dtype.newbyteorder(">")
            nel = int(np.prod(arr.shape[1:], dtype=int)) if arr.ndim > 1 \
                else 1
            code = f"{nel}{_CODE[be]}"
            width = nel * be.itemsize
        cards.append(_card(f"TTYPE{i}", name))
        cards.append(_card(f"TFORM{i}", code))
        if name in tdim_overrides:
            cards.append(_card(f"TDIM{i}", tdim_overrides[name]))
        for base, val in col_cards.get(name, {}).items():
            cards.append(_card(f"{base}{i}", val))
        fields.append((name, arr))
        stride += width
    head = [_card("XTENSION", "BINTABLE"), _card("BITPIX", 8),
            _card("NAXIS", 2), _card("NAXIS1", stride),
            _card("NAXIS2", nrows), _card("PCOUNT", 0),
            _card("GCOUNT", 1), _card("TFIELDS", len(columns)),
            _card("EXTNAME", extname)]
    head += cards + [_card(*c) for c in extra_cards]
    body = bytearray()
    for r in range(nrows):
        for name, arr in fields:
            a = arr[r]
            if arr.dtype.kind == "S":
                body += bytes(a)
            else:
                body += np.ascontiguousarray(
                    a, arr.dtype.newbyteorder(">")).tobytes()
    pad = (-len(body)) % BLOCK
    body += b"\x00" * pad
    return _header_bytes(head) + bytes(body)


def gaussian_portrait(nchan, nbin, amp=5.0, loc=0.3, wid=0.04):
    """A simple unscattered Gaussian portrait with a linear amplitude
    gradient across channels — analytic, so tests can recompute the
    expected loaded values independently."""
    x = (np.arange(nbin) + 0.5) / nbin
    prof = amp * np.exp(-0.5 * ((x - loc) / wid) ** 2)
    scales = 1.0 + 0.5 * np.linspace(-1, 1, nchan)
    return scales[:, None] * prof[None, :]


def forge_archive(path, nsub=2, nchan=8, nbin=64, npol=1,
                  pol_type="INTEN", fd_poln=None, data_maker=None,
                  data_dtype=">i2", with_wts=True, with_scl_offs=True,
                  tdim_style=None, ragged_freqs=False, freq0=1400.0,
                  chan_bw=25.0, period=0.005, dm=12.5, dedisp=0,
                  polyco_rows=0, extra_primary=(), src="FORGE",
                  extra_subint_cards=(), omit_dm_card=False,
                  data_tscal=None, data_tzero=None, quant_span=None):
    """Write a hand-forged PSRFITS fold-mode archive and return the
    float64 data cube a correct loader should produce (after DAT_SCL /
    DAT_OFFS application, before any baseline removal).

    data_maker(isub, ipol) -> (nchan, nbin) float array of TRUE values.
    data_dtype: '>i2' (scaled int16), 'u1' (scaled unsigned byte),
    'i1' (SIGNED byte via the FITS TZERO=-128 convention — stored
    unsigned, physical = stored - 128), '>f4' (float samples, unit
    scale), or 'nbit1'/'nbit2'/'nbit4' (sub-byte packed unsigned
    samples, MSB-first, NBIT card written).
    data_tscal/data_tzero: GENERAL FITS column scaling on the integer
    DATA column (TSCALn/TZEROn cards beyond the signed-byte
    convention): physical = (stored*TSCAL + TZERO)*DAT_SCL + DAT_OFFS
    — the layout the raw lane ships with its two scaling scalars.
    quant_span: quantize to this many stored levels instead of the
    dtype's full range (a coarsely-quantizing backend; the dynamic
    range the transport codec packs).
    extra_subint_cards: appended to the SUBINT header (CHAN_DM,
    REF_FREQ, EPOCHS, ...).  omit_dm_card drops the SUBINT DM card so
    fallback chains (CHAN_DM, PSRPARAM) are exercised.
    chan_bw < 0 forges a descending-frequency band (OBSBW negative).
    """
    rng = np.random.default_rng(7)
    if data_maker is None:
        base = gaussian_portrait(nchan, nbin)

        def data_maker(isub, ipol):  # noqa: F811
            return base * (1.0 + 0.1 * ipol) + 0.1 * isub

    true = np.empty((nsub, npol, nchan, nbin))
    for s in range(nsub):
        for p in range(npol):
            true[s, p] = data_maker(s, p)

    nbit = None
    signed_byte = str(data_dtype) == "i1"
    if signed_byte:
        data_dtype = "u1"  # stored unsigned; TZERO=-128 restores sign
    if str(data_dtype).startswith("nbit"):
        nbit = int(str(data_dtype)[4:])
        data_dtype = "u1"
    dt = np.dtype(data_dtype)
    data = np.empty((nsub, npol, nchan, nbin), dt)
    scl = np.ones((nsub, npol, nchan), ">f4")
    offs = np.zeros((nsub, npol, nchan), ">f4")
    if nbit:
        lo = true.min(axis=-1)
        hi = true.max(axis=-1)
        span = float(2 ** nbit - 1)
        s_ = np.maximum((hi - lo) / span, 1e-12)
        q = np.clip(np.round((true - lo[..., None]) / s_[..., None]),
                    0, span)
        scl[:] = s_.astype(">f4")
        offs[:] = lo.astype(">f4")
        stored = q * s_[..., None] + lo[..., None]
        # pack MSB-first, each ROW padded to whole bytes (the PSRFITS
        # convention the reader must trim)
        per = 8 // nbit
        row_samp = npol * nchan * nbin
        row_bytes = (row_samp + per - 1) // per
        flat = q.astype(np.uint8).reshape(nsub, row_samp)
        padded = np.zeros((nsub, row_bytes * per), np.uint8)
        padded[:, :row_samp] = flat
        grp = padded.reshape(nsub, row_bytes, per)
        shifts = np.arange(per - 1, -1, -1, dtype=np.uint8) * nbit
        data = np.zeros((nsub, row_bytes), np.uint8)
        for j in range(per):
            data |= (grp[:, :, j] & ((1 << nbit) - 1)) << shifts[j]
    elif dt.kind == "f":
        data[:] = true.astype(dt)
        stored = data.astype(np.float64)
    elif signed_byte:
        # physical sample values span [-120, 120]; stored = phys + 128
        lo = true.min(axis=-1)
        hi = true.max(axis=-1)
        s_ = np.maximum((hi - lo) / 240.0, 1e-12)
        o_ = (hi + lo) / 2.0
        q = np.clip(np.round((true - o_[..., None]) / s_[..., None]),
                    -120, 120)
        data[:] = (q + 128).astype(dt)
        scl[:] = s_.astype(">f4")
        offs[:] = o_.astype(">f4")
        stored = q.astype(np.float64) * s_[..., None] + o_[..., None]
    else:
        lo = true.min(axis=-1)
        hi = true.max(axis=-1)
        span = {1: 250.0, 2: 65000.0}[dt.itemsize]
        zero = {1: 125.0, 2: 0.0}[dt.itemsize]  # u1 is offset-binary
        if quant_span is not None:
            # coarse quantization: fewer stored levels than the dtype
            # allows — the stored values' dynamic range is quant_span
            span = float(quant_span)
        s_ = np.maximum((hi - lo) / span, 1e-12)
        o_ = (hi + lo) / 2.0
        q = np.round((true - o_[..., None]) / s_[..., None] + zero)
        data[:] = q.astype(dt)
        dat_scl = s_
        dat_offs = o_ - zero * s_
        if data_tscal is not None or data_tzero is not None:
            # general column scaling: the host decode is
            # (q*TSCAL + TZERO)*DAT_SCL + DAT_OFFS, so fold the
            # inverse into the written DAT_SCL/DAT_OFFS — the stored
            # integers (and the returned truth) are unchanged
            ts = 1.0 if data_tscal is None else float(data_tscal)
            tz = 0.0 if data_tzero is None else float(data_tzero)
            if signed_byte:
                raise ValueError("data_tscal/tzero cannot combine "
                                 "with the signed-byte convention")
            dat_scl = s_ / ts
            dat_offs = dat_offs - tz * dat_scl
        scl[:] = dat_scl.astype(">f4")
        offs[:] = dat_offs.astype(">f4")
        if data_tscal is not None or data_tzero is not None:
            # truth through the f32 DAT_SCL/DAT_OFFS the file carries
            # (the folded inverse is not exactly representable in f32)
            sclf = dat_scl.astype(">f4").astype(np.float64)
            offf = dat_offs.astype(">f4").astype(np.float64)
            stored = (q * ts + tz) * sclf[..., None] + offf[..., None]
        else:
            stored = q.astype(np.float64) * s_[..., None] + \
                (o_ - zero * s_)[..., None]
    if not with_scl_offs and dt.kind != "f":
        raise ValueError("integer DATA without DAT_SCL makes no sense")

    freqs = freq0 + chan_bw * np.arange(nchan)
    dat_freq = np.tile(freqs, (nsub, 1)).astype(">f8")
    if ragged_freqs:
        # each subint slides by a quarter channel (Doppler tracking)
        for s in range(nsub):
            dat_freq[s] += 0.25 * chan_bw * s

    cols = [("TSUBINT", np.full(nsub, 10.0, ">f8")),
            ("OFFS_SUB", (np.arange(nsub) * 10.0 + 5.0).astype(">f8")),
            ("PERIOD", np.full(nsub, period, ">f8")),
            ("DAT_FREQ", dat_freq)]
    if with_wts:
        wts = np.ones((nsub, nchan), ">f4")
        wts[:, 0] = 0.0  # one zapped channel, so weights are visible
        cols.append(("DAT_WTS", wts))
    if with_scl_offs and dt.kind != "f":
        cols.append(("DAT_SCL", scl.reshape(nsub, npol * nchan)))
        cols.append(("DAT_OFFS", offs.reshape(nsub, npol * nchan)))
    cols.append(("DATA", data if nbit
                 else data.reshape(nsub, npol * nchan * nbin)))

    tdims = {}
    if tdim_style == "spaced":
        tdims["DATA"] = f"( {nbin} , {nchan} , {npol} )"
    elif tdim_style == "plain":
        tdims["DATA"] = f"({nbin},{nchan},{npol})"

    sub_cards = [("NCHAN", nchan), ("NPOL", npol), ("NBIN", nbin),
                 ("POL_TYPE", pol_type),
                 ("CHAN_BW", chan_bw), ("DEDISP", dedisp),
                 ("TBIN", period / nbin)]
    if not omit_dm_card:
        sub_cards.insert(4, ("DM", dm))
    sub_cards += list(extra_subint_cards)
    if nbit:
        sub_cards.append(("NBIT", nbit))
    prim = [("TELESCOP", "GBT"), ("SRC_NAME", src),
            ("OBSFREQ", float(freqs.mean())),
            ("OBSBW", chan_bw * nchan), ("FRONTEND", "RCVR"),
            ("BACKEND", "FORGE"),
            ("STT_IMJD", 55000), ("STT_SMJD", 3600),
            ("STT_OFFS", 0.0), ("OBS_MODE", "PSR")]
    if fd_poln:
        prim.append(("FD_POLN", fd_poln))
    prim += list(extra_primary)

    ccards = {"DATA": {"TZERO": -128.0}} if signed_byte else None
    if data_tscal is not None or data_tzero is not None:
        dc = {}
        if data_tscal is not None:
            dc["TSCAL"] = float(data_tscal)
        if data_tzero is not None:
            dc["TZERO"] = float(data_tzero)
        ccards = {"DATA": dc}
    blobs = [primary_hdu(prim),
             bintable_hdu("SUBINT", cols, extra_cards=sub_cards,
                          tdim_overrides=tdims, col_cards=ccards)]
    if polyco_rows:
        # multi-row POLYCO: blocks at successive epochs, constant spin
        f0 = 1.0 / period
        ncoef = 3
        pc = [("NSPAN", np.full(polyco_rows, 60.0, ">f8")),
              ("NCOEF", np.full(polyco_rows, ncoef, ">i2")),
              ("REF_MJD", (55000.0 + 0.04 + 0.04 * np.arange(
                  polyco_rows)).astype(">f8")),
              ("REF_PHS", np.zeros(polyco_rows, ">f8")),
              ("REF_F0", np.full(polyco_rows, f0, ">f8")),
              ("COEFF", np.zeros((polyco_rows, ncoef), ">f8"))]
        blobs.append(bintable_hdu("POLYCO", pc))

    with open(path, "wb") as f:
        for b in blobs:
            f.write(b)
    return stored, freqs


def forge_search_mode(path, nchan=8, nsblk=128):
    """A minimal SEARCH-mode PSRFITS file: OBS_MODE=SEARCH, a SUBINT
    table of unfolded filterbank sample blocks (NSBLK time samples per
    row, TBIN sampling, no PERIOD/NBIN fold structure).  Loaders must
    REFUSE it with a clear error, not misparse the samples as folded
    profiles."""
    nrows = 2
    data = np.zeros((nrows, nsblk * nchan), "u1")
    cols = [("TSUBINT", np.full(nrows, nsblk * 64e-6, ">f8")),
            ("OFFS_SUB", np.arange(nrows).astype(">f8")),
            ("DAT_FREQ", np.tile(1400.0 + 25.0 * np.arange(nchan),
                                 (nrows, 1)).astype(">f8")),
            ("DATA", data)]
    sub = [("NCHAN", nchan), ("NPOL", 1), ("NBIT", 8),
           ("NSBLK", nsblk), ("TBIN", 64e-6), ("CHAN_BW", 25.0)]
    prim = [("TELESCOP", "GBT"), ("SRC_NAME", "FORGE"),
            ("OBS_MODE", "SEARCH"), ("OBSFREQ", 1487.5),
            ("OBSBW", 200.0), ("STT_IMJD", 55000), ("STT_SMJD", 0),
            ("STT_OFFS", 0.0)]
    with open(path, "wb") as f:
        f.write(primary_hdu(prim))
        f.write(bintable_hdu("SUBINT", cols, extra_cards=sub))
