"""pptoas — measure wideband TOAs and DMs.

Flag parity: reference pptoas.py:1479-1687 (same dests/defaults; the
scipy `method` knob has no analogue in the fused-Newton engine and is
accepted-but-ignored for script compatibility; the TNC `bounds`
capability is exposed as --bound).
"""

import argparse
import sys

import numpy as np


def build_parser():
    p = argparse.ArgumentParser(
        prog="pptoas", description=__doc__.splitlines()[0])
    p.add_argument("-d", "--datafiles", metavar="archive", required=True,
                   help="PSRFITS archive or metafile of archive names.")
    p.add_argument("-m", "--modelfile", metavar="model", required=True,
                   help=".gmodel, spline model, or PSRFITS template.")
    p.add_argument("-o", "--outfile", metavar="timfile", default=None,
                   help="Output .tim file (appends). [default=stdout]")
    p.add_argument("--narrowband", action="store_true", default=False,
                   help="Make narrowband (per-channel) TOAs instead.")
    p.add_argument("--errfile", metavar="errfile", default=None,
                   help="Write fitted DM errors to this file (appends).")
    p.add_argument("-T", "--tscrunch", action="store_true", default=False,
                   help="tscrunch archives before measurement.")
    p.add_argument("-f", "--format", dest="format", default="ipta",
                   choices=("ipta", "princeton"),
                   help="Output TOA format.")
    p.add_argument("--nu_ref", dest="nu_ref_DM", default=None,
                   help="Output reference frequency [MHz]; 'inf' for "
                        "infinite. [default: zero-covariance frequency]")
    p.add_argument("--DM", dest="DM0", default=None, type=float,
                   help="Nominal DM [cm**-3 pc] for offset-DM reporting.")
    p.add_argument("--no_bary", dest="bary", action="store_false",
                   default=True, help="No Doppler correction of DM/GM/tau.")
    p.add_argument("--one_DM", action="store_true", default=False,
                   help="Single (mean) DM value per epoch in the .tim.")
    p.add_argument("--fix_DM", dest="fit_DM", action="store_false",
                   default=True, help="Do not fit for DM.")
    p.add_argument("--fit_dt4", dest="fit_GM", action="store_true",
                   default=False, help="Fit nu**-4 'GM' delays.")
    p.add_argument("--fit_scat", action="store_true", default=False,
                   help="Fit scattering timescale and index per TOA.")
    p.add_argument("--no_logscat", dest="log10_tau", action="store_false",
                   default=True, help="Fit tau linearly, not log10(tau).")
    p.add_argument("--scat_guess", default=None,
                   help="'tau[s],freq[MHz],alpha' initial scattering "
                        "guess, or 'auto' to estimate it per subint from "
                        "the data's harmonic amplitude decay.")
    p.add_argument("--fix_alpha", action="store_true", default=False,
                   help="Hold the scattering index fixed (with --fit_scat).")
    p.add_argument("--nu_tau", dest="nu_ref_tau", default=None, type=float,
                   help="Output reference frequency [MHz] for tau.")
    p.add_argument("--print_phase", action="store_true", default=False,
                   help="Add -phs/-phs_err flags to TOA lines.")
    p.add_argument("--print_flux", action="store_true", default=False,
                   help="Add flux-estimate flags to TOA lines.")
    p.add_argument("--print_parangle", action="store_true", default=False,
                   help="Add parallactic-angle flags to TOA lines.")
    p.add_argument("--flags", default="",
                   help="Comma-separated extra TOA flag pairs k1,v1,k2,v2.")
    p.add_argument("--snr_cut", dest="snr_cutoff", default=0.0, type=float,
                   help="Minimum snr flag value for written TOAs.")
    p.add_argument("--showplot", action="store_true", default=False,
                   help="Save per-subint fit plots next to the archives.")
    p.add_argument("--prefetch", action="store_true", default=False,
                   help="Overlap archive IO with fitting (long lists).")
    p.add_argument("--stream", action="store_true", default=False,
                   help="Cross-archive batched dispatches for large "
                        "campaigns (wideband phi/DM fits only).")
    p.add_argument("--stream-devices", dest="stream_devices",
                   default=None, metavar="auto|N",
                   help="With --stream: local devices to deal fused "
                        "buckets across, round-robin ('auto' = all "
                        "local devices of the default backend, or an "
                        "explicit count).  Output is digit-identical "
                        "for any value. [default: config.stream_devices"
                        " / PPT_STREAM_DEVICES]")
    p.add_argument("--pipeline-depth", dest="pipeline_depth",
                   default=None, type=int, metavar="N",
                   help="With --stream: per-device transfer-pipeline "
                        "depth — how many buckets may occupy a "
                        "device's copy->fit pipeline at once (2 "
                        "double-buffers h2d against in-flight fits, 1 "
                        "serializes the stages; output is byte-"
                        "identical for any value). [default: "
                        "config.stream_pipeline_depth / "
                        "PPT_PIPELINE_DEPTH]")
    p.add_argument("--fit-fused", dest="fit_fused", default=None,
                   metavar="off|auto|on",
                   help="Fused (hand-blocked single-program) DFT -> "
                        "cross-spectrum hot path for the fast fit "
                        "lanes (ops/fused.py; active only with the "
                        "harmonic window, where .tim output is byte-"
                        "identical fused vs unfused): 'off', 'auto' "
                        "(TPU backends), 'on'.  Also via "
                        "PPT_FIT_FUSED / config.fit_fused. [default: "
                        "config.fit_fused]")
    p.add_argument("--transport-compress", dest="transport_compress",
                   default=None, metavar="off|auto|on",
                   help="With --stream: lossless transport codec for "
                        "the h2d copy stage (io/blockcodec width "
                        "reduction, decoded on device inside the "
                        "fused program): 'off', 'auto' (a cost model "
                        "fed from live h2d MB/s telemetry engages it "
                        "only when predicted to win), 'on' (always "
                        "when compressible — the A/B arm).  .tim "
                        "output is digit-identical either way.  Also "
                        "via PPT_TRANSPORT_COMPRESS / "
                        "config.transport_compress. [default: off]")
    p.add_argument("--compile-cache", dest="compile_cache",
                   default=None, metavar="DIR",
                   help="Persistent jax compilation cache directory: "
                        "re-runs skip the per-(bucket shape x device) "
                        "XLA compile cold start.  Also via "
                        "PPT_COMPILE_CACHE / config.compile_cache_dir."
                        " [default: off]")
    p.add_argument("--autotune", action="store_true", default=False,
                   help="Before the campaign, resolve this backend's "
                        "measured knob winners from the tuning DB "
                        "(--tune-db / PPT_TUNE_DB); with no stored "
                        "entry, sweep the output-identity-preserving "
                        "knob tier on the first archive and persist "
                        "the winners.  .tim output is byte-identical "
                        "tuned vs default.  Also via PPT_AUTOTUNE.")
    p.add_argument("--tune-db", dest="tune_db", default=None,
                   metavar="PATH",
                   help="Persisted per-backend tuning DB (JSON, "
                        "tune/store.py).  A DB measured on a "
                        "different backend fingerprint is refused "
                        "with a warning.  Also via PPT_TUNE_DB. "
                        "[default: config.tune_db]")
    p.add_argument("--bound", action="append", default=[],
                   metavar="PARAM:LO,HI",
                   help="Box bound on a fit parameter (repeatable): "
                        "PARAM in {phi,dm,gm,tau,alpha}; LO/HI are "
                        "floats or 'None' (open).  tau bounds are in "
                        "log10(rotations) under the default log-tau "
                        "parameterization.  The reference's TNC "
                        "`bounds` capability; a fit converging on a "
                        "bound reports return code 0 (LOCALMINIMUM).")
    p.add_argument("--telemetry", metavar="trace.jsonl", default=None,
                   help="Write a structured JSONL campaign trace "
                        "(per-bucket dispatch/drain, per-archive "
                        "prepare/flush/skip, per-TOA quality) to this "
                        "path; analyze with tools/pptrace.py.  Also "
                        "via PPT_TELEMETRY / config.telemetry_path. "
                        "[default: off]")
    p.add_argument("--quality_flags", action="store_true", default=False,
                   help="Add per-TOA -nfev/-chi2 fit-diagnostic flags "
                        "to the .tim lines (wideband paths; -snr/-gof "
                        "are always present). [default: off]")
    p.add_argument("--quiet", action="store_true", default=False)
    # accepted for reference-script compatibility; no-ops here:
    p.add_argument("--psrchive", action="store_true", default=False,
                   help=argparse.SUPPRESS)
    p.add_argument("--method", default=None, help=argparse.SUPPRESS)
    return p


_BOUND_PARAMS = {"phi": 0, "dm": 1, "gm": 2, "tau": 3, "alpha": 4}


def parse_bounds(specs):
    """--bound PARAM:LO,HI strings -> (5, 2) array or None."""
    if not specs:
        return None
    bounds = np.full((5, 2), (-np.inf, np.inf))
    for spec in specs:
        try:
            name, rng = spec.split(":")
            lo, hi = rng.split(",")
            idx = _BOUND_PARAMS[name.strip().lower()]
            lo_v = (-np.inf if lo.strip().lower() in ("none", "")
                    else float(lo))
            hi_v = (np.inf if hi.strip().lower() in ("none", "")
                    else float(hi))
        except (ValueError, KeyError):
            raise SystemExit(
                f"--bound: expected PARAM:LO,HI with PARAM in "
                f"{sorted(_BOUND_PARAMS)}; got {spec!r}")
        if np.isnan(lo_v) or np.isnan(hi_v):
            raise SystemExit(f"--bound: NaN bound in {spec!r}")
        if lo_v > hi_v:
            raise SystemExit(
                f"--bound: lower bound exceeds upper in {spec!r}")
        bounds[idx] = (lo_v, hi_v)
    return bounds


def _tune_workload(args):
    """Representative --autotune sweep workload: fit the FIRST archive
    through the same streaming lane the campaign will use, returning
    the .tim bytes as the identity artifact the sweep's byte gate
    compares (tune/autotune.py).  The shape class is the archive's
    (nchan, nbin) — the same key the benches persist under."""
    import os
    import tempfile

    from ..io.psrfits import load_data
    from ..pipeline.toas import _is_metafile, _read_metafile
    from ..tune import shape_class_for, tuned_config

    datafiles = args.datafiles
    if isinstance(datafiles, str):
        datafiles = (_read_metafile(datafiles)
                     if _is_metafile(datafiles) else [datafiles])
    first = datafiles[0]
    d = load_data(first, quiet=True)
    shape_class = shape_class_for(d.nchan, d.nbin)
    tmpdir = tempfile.mkdtemp(prefix="ppt_tune_")
    tim = os.path.join(tmpdir, "probe.tim")

    def run_fn(overrides):
        with tuned_config(overrides):
            if args.narrowband:
                from ..pipeline.stream import stream_narrowband_TOAs

                stream_narrowband_TOAs(
                    [first], args.modelfile,
                    tscrunch=args.tscrunch, tim_out=tim, quiet=True)
            else:
                from ..pipeline.stream import stream_wideband_TOAs

                stream_wideband_TOAs(
                    [first], args.modelfile,
                    tscrunch=args.tscrunch, tim_out=tim, quiet=True)
        with open(tim, "rb") as fh:
            return fh.read()

    return run_fn, shape_class


def _apply_autotune(args):
    """Resolve tuned knob winners BEFORE the campaign (--autotune /
    --tune-db): stored DB winners for this backend apply directly;
    with --autotune and no stored entry the output-identity-preserving
    knob tier is swept on the first archive and the winners persisted.

    Returns ``(tracer, owned)``.  When tuning is active and telemetry
    is on, ONE tracer is resolved here so the tune_probe/tune_apply
    witness lands in the SAME trace the campaign driver writes —
    main() hands the driver the tracer object (not the path; a second
    Tracer on the path would rotate the tune events away) and closes
    it after the lane returns."""
    from .. import config

    if args.tune_db is not None:
        config.tune_db = args.tune_db
    if args.autotune:
        config.autotune = True
    from ..telemetry import NULL_TRACER

    if not (config.autotune or config.tune_db):
        return NULL_TRACER, False
    from ..telemetry import resolve_tracer

    tracer, owned = resolve_tracer(args.telemetry, run="pptoas")
    if config.autotune:
        from ..tune import ensure_tuned

        run_fn, shape_class = _tune_workload(args)
        ensure_tuned(run_fn, shape_class, tracer=tracer)
    else:
        # --tune-db without --autotune: apply stored winners, never
        # sweep — a cold/foreign DB is a no-op (the store warns)
        from ..tune import apply_from_db

        apply_from_db(tracer=tracer)
    return tracer, owned


def main(argv=None):
    args = build_parser().parse_args(argv)
    from ..io.tim import write_princeton_TOAs, write_TOAs
    from ..pipeline import GetTOAs

    nu_ref_DM = args.nu_ref_DM
    if nu_ref_DM is not None:
        nu_ref_DM = np.inf if str(nu_ref_DM).lower() == "inf" \
            else float(nu_ref_DM)
    nu_refs = None
    if nu_ref_DM is not None or args.nu_ref_tau is not None:
        nu_refs = (nu_ref_DM, args.nu_ref_tau)
    scat_guess = None
    if args.scat_guess:
        scat_guess = ("auto" if args.scat_guess.strip() == "auto"
                      else [float(x) for x in args.scat_guess.split(",")])
    addtnl = {}
    if args.flags:
        parts = args.flags.split(",")
        addtnl = dict(zip(parts[0::2], parts[1::2]))
    bounds = parse_bounds(args.bound)
    if bounds is not None and (args.stream or args.narrowband
                               or args.psrchive):
        raise SystemExit("--bound applies to the standard wideband "
                         "GetTOAs path (no --stream/--narrowband)")
    stream_devices = args.stream_devices
    if stream_devices is not None:
        if not args.stream:
            raise SystemExit("--stream-devices requires --stream")
        s = stream_devices.strip().lower()
        if s == "auto":
            stream_devices = "auto"
        else:
            try:
                stream_devices = int(s)
            except ValueError:
                raise SystemExit("--stream-devices: expected 'auto' or "
                                 f"a positive count, got "
                                 f"{args.stream_devices!r}")
            if stream_devices < 1:
                raise SystemExit("--stream-devices: count must be "
                                 f">= 1, got {stream_devices}")
    if args.pipeline_depth is not None:
        if not args.stream:
            raise SystemExit("--pipeline-depth requires --stream")
        if args.pipeline_depth < 1:
            raise SystemExit("--pipeline-depth: depth must be >= 1, "
                             f"got {args.pipeline_depth}")
    if args.transport_compress is not None:
        if not args.stream:
            raise SystemExit("--transport-compress requires --stream "
                             "(the codec rides the streaming copy "
                             "stage)")
        table = {"off": False, "auto": "auto", "on": True}
        v = str(args.transport_compress).lower()
        if v not in table:
            raise SystemExit("--transport-compress expected one of "
                             "off/auto/on, got "
                             f"{args.transport_compress!r}")
        from .. import config

        config.transport_compress = table[v]
    if args.fit_fused is not None:
        table = {"off": False, "auto": "auto", "on": True}
        v = str(args.fit_fused).lower()
        if v not in table:
            raise SystemExit("--fit-fused expected one of off/auto/on, "
                             f"got {args.fit_fused!r}")
        # resolved per trace by the fast lanes (fit.portrait
        # .use_fit_fused), so the config value routes every fit of
        # this process
        from .. import config

        config.fit_fused = table[v]
    if args.compile_cache:
        # applies to EVERY lane (GetTOAs compiles too); also sets the
        # config default so spawned helpers resolve the same cache
        from .. import config
        from ..utils.device import enable_compile_cache

        config.compile_cache_dir = args.compile_cache
        enable_compile_cache(args.compile_cache)

    if args.quality_flags and args.narrowband:
        raise SystemExit("--quality_flags applies to the wideband "
                         "paths (per-channel lines already carry "
                         "-snr/-gof)")
    if args.narrowband and not args.stream:
        if args.telemetry:
            raise SystemExit("--telemetry covers the wideband GetTOAs "
                             "path and the --stream drivers (use "
                             "--stream --narrowband for traced "
                             "per-channel campaigns)")
        from .. import config
        if config.telemetry_path:
            # PPT_TELEMETRY / config.telemetry_path set, but this path
            # emits no trace — say so instead of being silently inert
            # (the same hazard the unknown-PPT_* warning exists for)
            from ..telemetry import log
            log("pptoas: telemetry_path is set but the non-stream "
                "narrowband path is untraced; use --stream "
                "--narrowband for a traced per-channel campaign",
                level="warn")

    # --autotune / --tune-db: resolve this backend's tuned knob
    # winners before any lane compiles; when tuning is active the
    # campaign shares the tracer resolved here (tune events + campaign
    # events, one trace)
    telemetry = args.telemetry
    tune_tracer, tune_owned = _apply_autotune(args)
    if tune_owned:
        telemetry = tune_tracer

    if args.stream and args.narrowband:
        if (args.psrchive or args.one_DM or args.print_flux
                or args.print_parangle or args.fit_GM or args.showplot):
            raise SystemExit(
                "--stream --narrowband supports per-channel (phi[, "
                "tau]) fits only (no psrchive/one_DM/flux/parangle/GM "
                "flags or plots)")
        from ..pipeline.stream import stream_narrowband_TOAs

        res = stream_narrowband_TOAs(
            args.datafiles, args.modelfile, fit_scat=args.fit_scat,
            log10_tau=args.log10_tau, scat_guess=scat_guess,
            tscrunch=args.tscrunch, stream_devices=stream_devices,
            pipeline_depth=args.pipeline_depth,
            print_phase=args.print_phase, addtnl_toa_flags=addtnl,
            telemetry=telemetry, quiet=args.quiet)
        if args.format == "princeton":
            write_princeton_TOAs(res.TOA_list, outfile=args.outfile,
                                 dDMs=[0.0] * len(res.TOA_list))
        else:
            write_TOAs(res.TOA_list, SNR_cutoff=args.snr_cutoff,
                       outfile=args.outfile, append=True)
        if tune_owned:
            tune_tracer.close()
        return 0

    if args.stream:
        if (args.psrchive
                or args.one_DM
                or args.print_parangle
                or args.showplot):
            raise SystemExit(
                "--stream supports the wideband (phi, DM[, GM, "
                "scattering], flux, phase) campaign configuration only "
                "(no one_DM/parangle flags or plots)")
        from ..pipeline.stream import stream_wideband_TOAs

        res = stream_wideband_TOAs(
            args.datafiles, args.modelfile, fit_DM=args.fit_DM,
            fit_GM=args.fit_GM, print_flux=args.print_flux,
            print_phase=args.print_phase,
            nu_ref_DM=nu_ref_DM, nu_ref_tau=args.nu_ref_tau,
            DM0=args.DM0, bary=args.bary,
            tscrunch=args.tscrunch, fit_scat=args.fit_scat,
            log10_tau=args.log10_tau, scat_guess=scat_guess,
            fix_alpha=args.fix_alpha, addtnl_toa_flags=addtnl,
            stream_devices=stream_devices,
            pipeline_depth=args.pipeline_depth,
            telemetry=telemetry,
            quality_flags=args.quality_flags, quiet=args.quiet)
        if args.format == "princeton":
            dDMs = [toa.DM - res.DM0s[res.order.index(toa.archive)]
                    if toa.DM is not None else 0.0
                    for toa in res.TOA_list]
            write_princeton_TOAs(res.TOA_list, outfile=args.outfile,
                                 dDMs=dDMs)
            if args.errfile:
                with open(args.errfile, "a") as f:
                    for toa in res.TOA_list:
                        if toa.DM_error is not None:
                            f.write(f"{toa.DM_error:.5e}\n")
        else:
            write_TOAs(res.TOA_list, SNR_cutoff=args.snr_cutoff,
                       outfile=args.outfile, append=True)
        if tune_owned:
            tune_tracer.close()
        return 0

    gt = GetTOAs(args.datafiles, args.modelfile, quiet=args.quiet)
    if args.narrowband or args.psrchive:
        gt.get_narrowband_TOAs(tscrunch=args.tscrunch,
                               fit_scat=args.fit_scat,
                               log10_tau=args.log10_tau,
                               scat_guess=scat_guess,
                               print_phase=args.print_phase,
                               addtnl_toa_flags=addtnl, quiet=args.quiet)
    else:
        gt.get_TOAs(tscrunch=args.tscrunch, nu_refs=nu_refs, DM0=args.DM0,
                    bary=args.bary, fit_DM=args.fit_DM, fit_GM=args.fit_GM,
                    fit_scat=args.fit_scat, log10_tau=args.log10_tau,
                    scat_guess=scat_guess, fix_alpha=args.fix_alpha,
                    print_phase=args.print_phase,
                    print_flux=args.print_flux,
                    print_parangle=args.print_parangle,
                    addtnl_toa_flags=addtnl, prefetch=args.prefetch,
                    quiet=args.quiet, bounds=bounds,
                    quality_flags=args.quality_flags,
                    telemetry=telemetry)
        if args.one_DM:
            gt.apply_one_DM()
    if args.format == "princeton":
        dDMs = [toa.DM - gt.DM0s[gt.order.index(toa.archive)]
                if toa.DM is not None else 0.0 for toa in gt.TOA_list]
        write_princeton_TOAs(gt.TOA_list, outfile=args.outfile, dDMs=dDMs)
        if args.errfile:
            with open(args.errfile, "a") as f:
                for toa in gt.TOA_list:
                    if toa.DM_error is not None:
                        f.write(f"{toa.DM_error:.5e}\n")
    else:
        write_TOAs(gt.TOA_list, SNR_cutoff=args.snr_cutoff,
                   outfile=args.outfile, append=True)
    if tune_owned:
        tune_tracer.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
