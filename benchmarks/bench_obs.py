"""Fleet observability overhead + fidelity benchmark (ISSUE 20
acceptance gates): the SAME routed campaign with the metrics registry
+ SLO tracking + distributed tracing fully ON vs fully OFF, enforcing
that observability is honest about its cost — identical science
output, bounded wall overhead, and lossless trace reconstruction.

Arms (one process, bench_router's virtual-device discipline):
  refs — one-shot ``stream_wideband_TOAs`` per unique archive: the
         fresh-fit ``.tim`` bytes BOTH routed arms are gated against.
  off  — router + PPT_NHOSTS emulated hosts, ``metrics=False``, no
         SLO targets, no telemetry: PPT_NREQ requests, baseline wall.
  on   — fresh router + hosts with ``metrics=True``, per-tenant SLO
         targets, and a telemetry trace per process (1 router + N
         hosts): the SAME request replay.

Gates (the first two always enforced; the third disableable):
  tim_identical — every ``.tim`` from BOTH arms must be byte-identical
         to its one-shot reference: the registry, the SLO observes,
         and the trace-id stamping may not perturb a single output
         byte.
  merge_ok — ``pptrace merge`` over the on-arm's 1+N traces must
         reconstruct 100% of the requests: every submitted request
         appears in the cross-host timeline exactly once, with its
         host-side serve span joined and a critical-path stage named
         (``merge_frac`` == 1.0).
  overhead_ok — the on-arm wall may exceed the off-arm wall by at most
         PPT_OBS_OVERHEAD_GATE percent (default 3; 0 disables for
         smoke shapes, where per-request jitter dwarfs the registry's
         nanoseconds).

The on-arm router additionally serves its fleet-wide ``metrics`` op
(what ``ppmon`` polls) while requests are in flight; the reply's
fleet/router quantiles and per-tenant SLO snapshot ride in the JSON
line.  Knobs via env: PPT_NARCH (4), PPT_NSUB (2), PPT_NCHAN (16),
PPT_NBIN (128), PPT_NREQ (8), PPT_NHOSTS (2),
PPT_OBS_OVERHEAD_GATE (3), PPT_CAMPAIGN_CACHE, PPT_TELEMETRY.
Prints ONE JSON line.
"""

import io
import json
import os
import shutil
import sys
import time

sys.path.insert(0, __file__.rsplit("/", 2)[0])


def _ensure_devices(n):
    """Force >= n virtual CPU devices BEFORE jax initializes (the
    bench_stream discipline) so each emulated host owns its own
    device."""
    if "jax" not in sys.modules:
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + f" --xla_force_host_platform_device_count={n}"
            ).strip()


def main():
    NHOSTS = max(2, int(os.environ.get("PPT_NHOSTS", 2)))
    _ensure_devices(NHOSTS)
    import pulseportraiture_tpu  # noqa: F401
    from pulseportraiture_tpu import config
    config.dft_precision = "default"
    config.cross_spectrum_dtype = "bfloat16"
    config.env_overrides()

    import jax

    from pulseportraiture_tpu import telemetry
    from pulseportraiture_tpu.io.gmodel import write_gmodel
    from pulseportraiture_tpu.obs.merge import merge_traces
    from pulseportraiture_tpu.pipeline import stream_wideband_TOAs
    from pulseportraiture_tpu.serve import (InProcTransport, ToaClient,
                                            ToaRouter, ToaServer)
    from pulseportraiture_tpu.synth import default_test_model
    from pulseportraiture_tpu.synth.archive import make_fake_pulsar

    NARCH = max(2, int(os.environ.get("PPT_NARCH", 4)))
    NSUB = int(os.environ.get("PPT_NSUB", 2))
    NCHAN = int(os.environ.get("PPT_NCHAN", 16))
    NBIN = int(os.environ.get("PPT_NBIN", 128))
    NREQ = max(2, int(os.environ.get("PPT_NREQ", 8)))
    GATE = float(os.environ.get("PPT_OBS_OVERHEAD_GATE", 3.0))
    PAR = {"PSR": "FAKE", "P0": 0.003, "DM": 50.0, "PEPOCH": 56000.0}
    cache = os.environ.get("PPT_CAMPAIGN_CACHE", "/tmp/ppt_campaign")
    tag = f"obs{NARCH}x{NSUB}x{NCHAN}x{NBIN}"
    root = os.path.join(cache, tag)
    os.makedirs(root, exist_ok=True)
    trace_base = config.telemetry_path  # PPT_TELEMETRY (or None)

    mpath = os.path.join(root, "model.gmodel")
    if not os.path.exists(mpath):
        write_gmodel(default_test_model(1500.0), mpath, quiet=True)
    files = []
    for i in range(NARCH):
        path = os.path.join(root, f"a{i:04d}.fits")
        if not os.path.exists(path):
            make_fake_pulsar(mpath, PAR, outfile=path, nsub=NSUB,
                             nchan=NCHAN, nbin=NBIN, nu0=1500.0,
                             bw=600.0, phase=0.01 * (i % 50),
                             dDM=1e-4 * (i % 40), noise_stds=0.05,
                             quiet=True, rng=i)
        files.append(path)
    seq = [j % NARCH for j in range(NREQ)]  # the request replay
    tenants = ["interactive", "bulk"]

    out_root = os.path.join(root, "obs_out")
    shutil.rmtree(out_root, ignore_errors=True)
    os.makedirs(out_root, exist_ok=True)

    def tim(arm, j):
        return os.path.join(out_root, f"{arm}_{j}.tim")

    # ---- one-shot references per unique archive --------------------
    ref_bytes = {}
    for i in range(NARCH):
        ref = tim("ref", i)
        stream_wideband_TOAs([files[i]], mpath, nsub_batch=64,
                             tim_out=ref, quiet=True)
        ref_bytes[i] = open(ref, "rb").read()

    def run_arm(arm, metrics, slo_targets, traced):
        """One routed replay; returns (wall_s, router, live_metrics,
        [trace paths]).  The caller closes the router."""
        rtrace = f"{trace_base}.obsr" if (trace_base and traced) \
            else None
        straces = [f"{trace_base}.obs{h}"
                   if (trace_base and traced) else None
                   for h in range(NHOSTS)]
        servers = [
            ToaServer(nsub_batch=64, quiet=True, metrics=metrics,
                      telemetry=straces[h],
                      stream_devices=[jax.local_devices()[h]]).start()
            for h in range(NHOSTS)]
        for s in servers:  # warm jit caches OUTSIDE the timed window
            ToaClient(s).get_TOAs([files[0]], mpath, timeout=600)
        router = ToaRouter(
            [InProcTransport(s, label=f"host{h}")
             for h, s in enumerate(servers)],
            metrics=metrics, slo_targets=slo_targets,
            telemetry=rtrace)
        t0 = time.perf_counter()
        handles = [router.submit([files[k]], mpath,
                                 tim_out=tim(arm, j), name=f"{arm}{j}",
                                 tenant=tenants[j % len(tenants)])
                   for j, k in enumerate(seq)]
        for h in handles:
            h.result(3600)
        wall = time.perf_counter() - t0
        live = router.metrics() if metrics else None
        router.close()
        for s in servers:
            s.stop()
        return wall, live, ([rtrace] + straces) if rtrace else []

    # ---- off arm: observability fully dark --------------------------
    off_wall, _, _ = run_arm("off", metrics=False, slo_targets=None,
                             traced=False)
    # ---- on arm: registry + SLO + tracing all live -------------------
    on_wall, live, traces = run_arm(
        "on", metrics=True,
        slo_targets={"interactive": 30.0, "bulk": 60.0}, traced=True)

    # ---- gate: byte-identity vs the one-shot references -------------
    tim_identical = all(
        open(tim(arm, j), "rb").read() == ref_bytes[k]
        for arm in ("off", "on") for j, k in enumerate(seq))
    assert tim_identical, (
        "a routed .tim diverged from its one-shot reference — the "
        "metrics/SLO/trace-id path perturbed the science output")

    # ---- gate: wall overhead of observability -----------------------
    overhead_pct = 100.0 * (on_wall - off_wall) / max(off_wall, 1e-9)
    overhead_ok = bool(overhead_pct <= GATE) if GATE > 0 else None
    assert overhead_ok is not False, (
        f"metrics-on replay cost {overhead_pct:.2f}% over the dark "
        f"arm (gate {GATE}%) — the registry is on the hot path")

    # ---- gate: 100% cross-host merge reconstruction -----------------
    merge_frac = None
    merge_ok = None
    n_slo_breach = 0
    if traces:
        merged = merge_traces(traces)
        # the warmup ToaClient fits also carry trace-ids (every
        # request does) — the gate is over the ROUTED replay: each
        # submitted request reconstructs EXACTLY once, with its
        # host-side serve span joined and a critical stage named
        per_name = {}
        for r in merged["requests"].values():
            per_name.setdefault(r["req"], []).append(r)
        want = {f"on{j}" for j in range(NREQ)}
        covered = sum(
            1 for n in want
            if len(per_name.get(n, ())) == 1
            and per_name[n][0]["n_host_spans"] >= 1
            and per_name[n][0]["critical"] is not None
            and per_name[n][0]["error"] is None)
        merge_frac = covered / NREQ
        merge_ok = merge_frac == 1.0
        assert merge_ok, (
            f"merge reconstructed {covered}/{NREQ} requests "
            f"({merged['n_requests']} timelines) — trace-id "
            "propagation dropped a request")
        summary = telemetry.report(traces[0], file=io.StringIO())
        n_slo_breach = summary["n_slo_breach"]

    # ---- the live fleet view ppmon polls ----------------------------
    fleet_view = None
    if live is not None:
        f, r = live["fleet"], live["router"]
        assert f["n_hosts"] == NHOSTS
        assert r["metrics"]["counters"]["route_done"] == NREQ
        assert f["p99_s"] is not None and r["p99_s"] is not None
        fleet_view = {
            "fleet_p50_s": f["p50_s"], "fleet_p99_s": f["p99_s"],
            "route_p50_s": r["p50_s"], "route_p99_s": r["p99_s"],
            "queue_depth": f["queue_depth"],
            "toas_per_s": f["toas_per_s"],
            "slo": {t: {"attainment": s["attainment"],
                        "alerting": s["alerting"]}
                    for t, s in (r["slo"] or {}).items()},
        }
        assert set(fleet_view["slo"]) == set(tenants)

    print(json.dumps({
        "metric": f"routed replay of {NREQ} requests over {NARCH} "
                  f"archives x {NSUB}sub x {NCHAN}ch x {NBIN}bin on "
                  f"{NHOSTS} emulated hosts, observability on vs off",
        "value": round(NREQ / max(on_wall, 1e-9), 2),
        "unit": "requests/sec",
        "off_requests_per_sec": round(NREQ / max(off_wall, 1e-9), 2),
        "overhead_pct": round(overhead_pct, 2),
        "overhead_ok": overhead_ok,
        "overhead_gate_pct": GATE,
        "tim_identical": bool(tim_identical),
        "merge_frac": merge_frac,
        "merge_ok": merge_ok,
        "n_traces_merged": len(traces),
        "n_slo_breach": n_slo_breach,
        "fleet_view": fleet_view,
        "device": str(jax.devices()[0]),
    }))


if __name__ == "__main__":
    main()
