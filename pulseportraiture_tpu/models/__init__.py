from .gaussian import (
    GaussianModel,
    evolve_parameter,
    power_law_evolution,
    linear_evolution,
    gen_gaussian_profile,
    gen_gaussian_portrait,
)

__all__ = [
    "GaussianModel",
    "evolve_parameter",
    "power_law_evolution",
    "linear_evolution",
    "gen_gaussian_profile",
    "gen_gaussian_portrait",
]
