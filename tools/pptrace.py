#!/usr/bin/env python
"""pptrace — analyze a pulseportraiture_tpu campaign telemetry trace.

Thin wrapper over ``python -m pulseportraiture_tpu.telemetry``:

    python tools/pptrace.py report  /path/to/trace.jsonl
    python tools/pptrace.py validate /path/to/trace.jsonl
    python tools/pptrace.py merge router.jsonl hostA.jsonl hostB.jsonl

Traces are written by the campaign drivers when telemetry is enabled
(``config.telemetry_path``, ``PPT_TELEMETRY=...``, ``pptoas
--telemetry PATH``, or ``ppserve --telemetry PATH``); see
docs/GUIDE.md "Tracing a campaign".  Serving-loop traces add a
"serve" report section: request-latency percentiles, queue-wait vs
serve split, batch occupancy, and the AOT warmup ledger.  Routed
traces add the "router" section (per-host shares, retry rate,
placement imbalance) and — for elastic fleets — the "fleet" section:
per-host health-state timeline (JOINING/HEALTHY/SUSPECT/DEAD/
REJOINED transitions), failover counts split collected-vs-
redispatched, hedge counts, and the per-tenant latency split; see
docs/GUIDE.md "Operating an elastic fleet".  Cache-enabled runs add
the "cache" section: hit rate over lookups, bytes served-from-cache
vs fitted-and-stored, the router/server hit split, per-tenant
hits-vs-fits, and eviction pressure; see docs/GUIDE.md "The result
cache".  SLO-tracked runs add the "slo" section (fast-burn breach
ledger).

``merge`` (ISSUE 20) stitches a router trace plus N host traces into
per-request CROSS-HOST span timelines joined on ``trace_id``: router
placement -> host queue wait -> serve -> wire+collect, with hedges,
failovers, and coalesced-batch membership called out and the
critical-path stage named per request (``--json`` for the raw merged
structure); see docs/GUIDE.md "Watching the fleet live".
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from pulseportraiture_tpu.telemetry import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main())
