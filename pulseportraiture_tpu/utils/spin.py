"""Exact rational spin-phase arithmetic.

frac(F0 * (epoch - PEPOCH)) is ~1e9 turns for an MSP campaign — one
f64 product aliases the fractional turn — so both producers of
absolute spin phase (synth.make_fake_pulsar's spin_coherent folding
and timing.gls's prefit residuals) reduce it in rational arithmetic
built from the SAME parfile-string representation.  Keeping a single
helper prevents the two sides drifting by the F0 float-rounding delta
(~F0 * 2^-53, a fake ~1 ns/100 days residual slope).
"""

from decimal import Decimal
from fractions import Fraction

__all__ = ["rational", "spin_F0", "spin_phase_frac", "day_phase_frac"]


def rational(v):
    """Exact Fraction from a parfile-style number: string (FORTRAN
    D-exponents included), float (exact binary value), int, or an
    already-converted Fraction (passed through)."""
    if isinstance(v, Fraction):
        return v
    if isinstance(v, float):
        return Fraction(v)
    return Fraction(Decimal(str(v).replace("D", "E").replace("d", "e")))


def spin_F0(par):
    """Exact F0 [Hz] as a Fraction from a parfile mapping (F0, else
    1/P0) — decimal-exact when the values are still strings."""
    if "F0" in par and par["F0"] is not None:
        return rational(par["F0"])
    return 1 / rational(par["P0"])


def spin_phase_frac(F0r, pepoch, day, frac_day):
    """frac(F0 * (epoch - PEPOCH)) in [0, 1), exactly.

    F0r: Fraction [Hz]; pepoch: parfile PEPOCH (any rational()-able
    value); day/frac_day: the epoch as (int MJD, f64 fractional day) —
    the framework's MJD representation."""
    dt_sec = (Fraction(int(day)) - rational(pepoch)) * 86400 \
        + Fraction(float(frac_day)) * 86400
    return float((F0r * dt_sec) % 1)


def day_phase_frac(F0r, pepoch_int_day, day):
    """frac(F0 * whole-day offset) in [0, 1), exactly — the
    integer-day part of the reduction, for callers that handle the
    sub-day remainder (< ~1e7 turns, safe in f64) separately."""
    return float((F0r * ((int(day) - int(pepoch_int_day)) * 86400)) % 1)
