"""Native (C++) SUBINT decode vs the pure-numpy reference path.

The native kernel in native/ppt_native.cpp must reproduce the numpy
decode bit-for-bit (both do big-endian int16 -> float64 * scl + offs
in IEEE double), so equality here is exact, not approximate.
"""

import numpy as np
import pytest

from pulseportraiture_tpu.io import fitsio, native, psrfits
from pulseportraiture_tpu.io.psrfits import read_archive

from test_io import _toy_archive

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native library unavailable (no g++?)"
)


def _numpy_read(path):
    """The pure-numpy reference decode, with the native path disabled."""
    orig = native.available
    native.available = lambda: False
    try:
        return read_archive(path)
    finally:
        native.available = orig


def test_decode_matches_numpy_reference(tmp_path):
    arch, amps, freqs, epochs = _toy_archive(nsub=4, nchan=16, nbin=128)
    path = str(tmp_path / "toy.fits")
    arch.unload(path)
    a_native = read_archive(path)
    a_numpy = _numpy_read(path)
    np.testing.assert_array_equal(a_native.amps, a_numpy.amps)
    np.testing.assert_array_equal(a_native.weights, a_numpy.weights)
    np.testing.assert_array_equal(a_native.freqs_table, a_numpy.freqs_table)


def test_decode_fused_against_manual(tmp_path):
    """Unit-level: decode_fused on a hand-built bintable buffer."""
    rng = np.random.default_rng(3)
    nsub, npol, nchan, nbin = 2, 1, 4, 32
    data = rng.integers(-32768, 32767, size=(nsub, npol, nchan, nbin))
    scl = rng.uniform(0.5, 2.0, size=(nsub, npol * nchan))
    offs = rng.normal(size=(nsub, npol * nchan))

    from collections import OrderedDict

    path = str(tmp_path / "tab.fits")
    cols = OrderedDict(
        DAT_SCL=scl.astype(">f4"),
        DATA=data.reshape(nsub, -1).astype(">i2"),
    )
    with open(path, "wb") as f:
        fitsio.write_primary(f, [])
        fitsio.write_bintable(f, "T", cols)
    hdu = fitsio.get_hdu(fitsio.read_fits(path, defer=("DATA",)), "T")
    assert hdu.data["DATA"] is None
    col_off, code, repeat = hdu.layout["DATA"]
    assert code == "I" and repeat == npol * nchan * nbin

    scl32 = scl.astype(">f4").astype(np.float64)  # what a reader would see
    out = native.decode_fused(
        hdu.raw, nsub, hdu.row_stride, col_off, code, npol, nchan, nbin,
        scl=scl32, offs=offs, dtype=np.float64)
    expect = (data.astype(np.float64)
              * scl32.reshape(nsub, npol, nchan)[..., None]
              + offs.reshape(nsub, npol, nchan)[..., None])
    np.testing.assert_array_equal(out, expect)

    # float32 output path
    out32 = native.decode_fused(
        hdu.raw, nsub, hdu.row_stride, col_off, code, npol, nchan, nbin,
        scl=scl32, offs=offs, dtype=np.float32)
    np.testing.assert_allclose(out32, expect.astype(np.float32), rtol=1e-6)


def test_declined_native_decode_uses_in_memory_fallback(tmp_path, monkeypatch):
    """If the native decode declines (e.g. unsupported sample type), the
    DATA column is decoded from the already-read table bytes — same
    result, no second disk read."""
    arch, amps, freqs, epochs = _toy_archive(nsub=2, nchan=8, nbin=64)
    path = str(tmp_path / "toy.fits")
    arch.unload(path)
    ref = read_archive(path)
    monkeypatch.setattr(native, "decode_fused",
                        lambda *a, **k: None)
    fb = read_archive(path)
    np.testing.assert_array_equal(fb.amps, ref.amps)


def test_unsupported_tform_falls_back(tmp_path):
    assert native._TFORM_CODE.get("D") is None
    with pytest.raises(ValueError):
        native.decode_fused(b"\0" * 16, 1, 16, 0, "D", 1, 1, 2)


def test_load_data_end_to_end_native(tmp_path):
    """load_data (the DataBunch entry point) works over the fast path."""
    arch, amps, freqs, epochs = _toy_archive()
    path = str(tmp_path / "toy.fits")
    arch.unload(path)
    d = psrfits.load_data(path, quiet=True, rm_baseline=False)
    scale = amps.max() - amps.min()
    np.testing.assert_allclose(
        d.subints, amps, atol=2e-4 * scale)
