"""ISM scattering/refraction helper quantities.

Capability parity with the reference's ISM helpers: mean_C2N and the
frequency-dependent delta-DM prediction (reference pplib.py:1221-1248,
Foster, Fairhead & Backer 1991; Cordes & Shannon 2010), and the
"discrete cloud" GM <-> DMc conversions (reference pptoaslib.py:93-121,
Lam et al. 2016).

These are scalar host-side convenience formulas (no hot path); plain
float math so they work on python scalars and numpy arrays alike.
"""

import numpy as np

# speed of light expressed as [cm/s] / [cm/kpc] (reference pptoaslib.py:105)
_C_KPC = 3e10 / 3.1e21
# 1 AU expressed in kpc (reference uses 4.8e-9 kpc/AU, pptoaslib.py:106)
_AU_KPC = 4.8e-9


def mean_C2N(nu, D, bw_scint):
    """Mean turbulence strength C_N^2 [m^-20/3] from the scintillation
    bandwidth (Foster, Fairhead & Backer 1991; reference pplib.py:1221).

    nu [MHz], D distance [kpc], bw_scint scintillation bandwidth [MHz].
    """
    return 2e-14 * nu ** (11 / 3.0) * D ** (-11 / 6.0) * bw_scint ** (-5 / 6.0)


def dDM(D, D_screen, nu, bw_scint):
    """Predicted frequency-dependent delta-DM [cm^-3 pc] from a thin
    scattering screen (Cordes & Shannon 2010; reference pplib.py:1235).

    D pulsar distance [kpc], D_screen Earth-screen distance [kpc],
    nu [MHz], bw_scint scintillation bandwidth at nu [MHz].
    """
    SM = mean_C2N(nu, D, bw_scint) * D  # scattering measure [m^-20/3 kpc]
    return 10**4.45 * SM * D_screen ** (5 / 6.0) * nu ** (-11 / 6.0)


def GM_from_DMc(DMc, D, a_perp):
    """Geometric delay factor GM from a discrete cloud of dispersion
    measure DMc (Lam et al. 2016; reference pptoaslib.py:93-106).

    The resulting pulse delay is Dconst**2 * GM * nu**-4.
    DMc [cm^-3 pc], D Earth-cloud distance [kpc], a_perp transverse
    scale [AU].
    """
    return DMc**2 * (_C_KPC * D) / (2.0 * (a_perp * _AU_KPC) ** 2)


def DMc_from_GM(GM, D, a_perp):
    """Discrete-cloud DM giving geometric delay factor GM — the exact
    inverse of GM_from_DMc.

    The reference's version (pptoaslib.py:109-121) mis-places a
    parenthesis (`2*a_perp*(4.8e-9)**2` instead of
    `2*(a_perp*4.8e-9)**2`) and so does not invert GM_from_DMc; this
    implementation is the consistent inverse (a documented defect fix).
    """
    return np.sqrt(GM * 2.0 * (a_perp * _AU_KPC) ** 2 / (_C_KPC * D))
