"""Diagnostic plots.

Parity targets: reference pplib.py:3652-4207 (show_portrait,
show_stacked_profiles, show_profiles, show_residual_plot,
show_spline_curve_projections, show_eigenprofiles) and the flux-profile
plot of fit_flux_profile (pplib.py:448-506).  All host-side matplotlib;
headless-safe (Agg) unless a display is configured.
"""

import os

import matplotlib

if not os.environ.get("DISPLAY"):
    matplotlib.use("Agg", force=False)

import matplotlib.pyplot as plt
import numpy as np


def set_colormap(name="viridis"):
    """Set the default image colormap (reference pplib.py:677)."""
    matplotlib.rcParams["image.cmap"] = name


def _finish(fig, show, savefig):
    if savefig:
        fig.savefig(savefig, bbox_inches="tight", dpi=120)
        plt.close(fig)
    elif show:
        plt.show()
    return fig


def show_portrait(port, phases=None, freqs=None, title=None, prof=True,
                  fluxprof=True, rvrsd=False, colorbar=True, show=True,
                  savefig=None, aspect="auto", interpolation="none",
                  origin="lower", extent=None, **kwargs):
    """Portrait image with average-profile (top) and phase-averaged-
    spectrum (left) side panels (reference pplib.py:3652-3757: same
    panel geometry, zero-weight channels compressed out of both side
    panels, rvrsd frequency flip, colorbar, extent override, and
    imshow passthrough kwargs e.g. vmin/vmax)."""
    port = np.asarray(port)
    nchan, nbin = port.shape
    if phases is None:
        phases = np.arange(nbin)
        xlabel = "Bin Number"
    else:
        phases = np.asarray(phases)
        xlabel = "Phase [rot]"
    if freqs is None:
        freqs = np.arange(nchan)
        ylabel = "Channel Number"
    else:
        freqs = np.asarray(freqs)
        ylabel = "Frequency [MHz]"
    if rvrsd:
        freqs = freqs[::-1]
        port = port[::-1]
    if extent is None:
        extent = (phases[0], phases[-1], freqs[0], freqs[-1])
    # zero-weight (zapped) channels carry no flux: compress them out
    # of the side panels exactly like the reference (weights = channel
    # means; np.compress)
    weights = port.mean(axis=1)
    portx = np.compress(weights, port, axis=0)
    fluxx = np.compress(weights, weights)
    freqsx = np.compress(weights, freqs)
    if portx.size == 0:  # fully zapped: fall back to raw panels
        portx, fluxx, freqsx = port, weights, freqs

    fig = plt.figure(figsize=(7.5, 6))
    gs = fig.add_gridspec(2 if prof else 1, 2 if fluxprof else 1,
                          width_ratios=([1, 3] if fluxprof else [1]),
                          height_ratios=([1, 3] if prof else [1]),
                          hspace=0.05, wspace=0.05)
    ax_im = fig.add_subplot(gs[-1, -1])
    im = ax_im.imshow(port, aspect=aspect, origin=origin, extent=extent,
                      interpolation=interpolation, **kwargs)
    if colorbar:
        fig.colorbar(im, ax=ax_im, pad=0.01)
    ax_im.set_xlabel(xlabel)
    if fluxprof:
        ax_im.tick_params(labelleft=False)
    else:
        ax_im.set_ylabel(ylabel)
    if prof:
        ax_p = fig.add_subplot(gs[0, -1], sharex=ax_im)
        avg = portx.mean(axis=0)
        ax_p.plot(phases, avg, "k-", lw=1)
        ax_p.tick_params(labelbottom=False)
        rng = avg.max() - avg.min()
        if rng > 0:  # a flat (fully-zapped) profile keeps auto limits
            ax_p.set_ylim(avg.min() - 0.03 * rng,
                          avg.max() + 0.05 * rng)
        ax_p.set_ylabel("Flux Units")
        if title:
            ax_p.set_title(title)
    elif title:
        ax_im.set_title(title)
    if fluxprof:
        ax_f = fig.add_subplot(gs[-1, 0], sharey=ax_im)
        # phase-averaged spectrum as markers, flux increasing LEFTWARD
        # (the reference's inverted x-axis, pplib.py:3741-3746)
        ax_f.plot(fluxx, freqsx, "kx", ms=4)
        rng = fluxx.max() - fluxx.min()
        if rng > 0:
            ax_f.set_xlim(fluxx.max() + 0.03 * rng,
                          min(fluxx.min(), 0.0) - 0.01 * rng)
        else:
            ax_f.invert_xaxis()
        ax_f.set_xlabel("Flux Units")
        ax_f.set_ylabel(ylabel)
    return _finish(fig, show, savefig)


def show_stacked_profiles(port, freqs=None, *, model_profiles=None,
                          phases=None, rvrsd=False, fit=False,
                          spacing=None, fact=0.25, show=True,
                          savefig=None, title=None):
    """Vertically offset per-channel profiles with optional overlaid
    model profiles (reference pplib.py:3760-3824: dashed model under
    solid data in matching colors; fit=True aligns/scales each model
    to its data profile via fit_phase_shift first; frequency tick
    labels every 10 channels; rvrsd flips the stack)."""
    port = np.asarray(port)
    nchan, nbin = port.shape
    models = None if model_profiles is None else \
        np.asarray(model_profiles)
    if phases is None:
        phases = np.arange(nbin)
        xlabel = "Bin Number"
    else:
        phases = np.asarray(phases)
        xlabel = "Phase [rot]"
    if freqs is None:
        freqs = np.arange(nchan)
        ylabel = "Approx. Channel Number"
    else:
        freqs = np.asarray(freqs)
        ylabel = "Approx. Frequency [MHz]"
    if rvrsd:
        freqs = freqs[::-1]
        port = port[::-1]
        if models is not None:
            models = models[::-1]
    if spacing is None:
        spacing = (port.max() - port.min()) * fact
    fig, ax = plt.subplots(figsize=(5, 8))
    for i in range(nchan):
        base = i * spacing
        if models is not None:
            mprof = models[i]
            if fit and np.any(port[i] - mprof):
                from ..fit import fit_phase_shift
                from ..ops import rotate_profile

                r = fit_phase_shift(port[i], mprof)
                mprof = float(r.scale) * np.asarray(
                    rotate_profile(mprof, -float(r.phase)))
            m, = ax.plot(phases, mprof + base, lw=1.2, ls="dashed")
            ax.plot(phases, port[i] + base, lw=0.8, ls="solid",
                    color=m.get_color())
        else:
            ax.plot(phases, port[i] + base, "k-", lw=0.6)
    ax.set_xlabel(xlabel)
    step = max(1, nchan // 10)
    ax.set_yticks(np.arange(nchan)[::step] * spacing)
    ax.set_yticklabels([str(int(round(f))) for f in freqs[::step]])
    ax.set_ylabel(ylabel)
    if title:
        ax.set_title(title)
    return _finish(fig, show, savefig)


def show_profiles(profiles, labels=None, show=True, savefig=None,
                  title=None):
    """Overlayed profiles (reference pplib.py:3827-3850)."""
    profiles = np.atleast_2d(np.asarray(profiles))
    nbin = profiles.shape[-1]
    phases = (np.arange(nbin) + 0.5) / nbin
    fig, ax = plt.subplots(figsize=(6, 4))
    for i, prof in enumerate(profiles):
        label = labels[i] if labels else None
        ax.plot(phases, prof, lw=1, label=label)
    ax.set_xlabel("Phase [rot]")
    ax.set_ylabel("Flux")
    if labels:
        ax.legend()
    if title:
        ax.set_title(title)
    return _finish(fig, show, savefig)


# composite red-chi2 histogram bin edges (reference pplib.py:3955-3957):
# fine [0, 2], coarser decades above, an overflow bin at the end
_RCHI2_BINS = np.concatenate([
    np.linspace(0.0, 2.0, 21), np.linspace(3.0, 10.0, 8),
    np.linspace(20.0, 100.0, 9), np.linspace(200.0, 1000.0, 9),
    [np.inf]])


def show_residual_plot(port, model, phases=None, freqs=None,
                       noise_stds=None, weights=None, titles=None,
                       resids=None, nfit=0, rvrsd=False, colorbar=True,
                       show=True, savefig=None, **imshow_kwargs):
    """Data / model / residual triptych with a per-channel reduced-chi2
    histogram (reference pplib.py:3853-3974; same behaviors):

    - the model panel shares the DATA panel's color limits, so over-
      and under-fitting are visible at a glance;
    - per-panel colorbars (colorbar=False to drop), rvrsd frequency
      flip, imshow passthrough kwargs (vmin/vmax/cmap/...);
    - axis labels fall back to bin/channel NUMBERS when phases/freqs
      are not given;
    - the histogram uses the reference's composite bins (fine to 2,
      decade blocks above, overflow at inf), a step outline, log x
      when the channel spread exceeds two decades, x-limits hugging
      [0.9 min, 1.1 max], and counts only unzapped channels
      ("# chans. (total = N)"); dof = nbin - nfit per channel.
    resids: precomputed residuals (default port - model); noise_stds:
    per-channel sigmas (None -> power-spectrum estimate); weights:
    channels with weight <= 0 are excluded from the histogram (the
    reference compresses on the row means)."""
    from ..ops.noise import get_noise_PS

    port = np.asarray(port)
    model = np.asarray(model)
    resid = np.asarray(resids) if resids is not None else port - model
    nchan, nbin = port.shape
    if phases is None:
        phases = np.arange(nbin)
        xlabel = "Bin Number"
    else:
        phases = np.asarray(phases)
        xlabel = "Phase [rot]"
    if freqs is None:
        freqs = np.arange(nchan)
        ylabel = "Channel Number"
    else:
        freqs = np.asarray(freqs)
        ylabel = "Frequency [MHz]"
    if noise_stds is not None:
        noise_stds = np.asarray(noise_stds)
    if rvrsd:
        freqs = freqs[::-1]
        port, model, resid = port[::-1], model[::-1], resid[::-1]
        if noise_stds is not None:
            noise_stds = noise_stds[::-1]
        if weights is not None:
            weights = np.asarray(weights)[::-1]
    extent = [phases[0], phases[-1], freqs[0], freqs[-1]]
    fig, axes = plt.subplots(2, 2, figsize=(8.5, 6.67))
    im0 = None
    panels = [(port, "Data"), (model, "Model"), (resid, "Residuals")]
    for i, (ax, (img, name)) in enumerate(zip(axes.flat, panels)):
        kw = dict(imshow_kwargs)
        if i == 1 and im0 is not None and "vmin" not in kw \
                and "norm" not in kw:
            # reference: the model panel inherits the data panel's clim
            # (skipped when the caller controls scaling via vmin/norm —
            # imshow rejects a norm combined with vmin/vmax)
            kw["vmin"], kw["vmax"] = im0.get_clim()
        im = ax.imshow(img, aspect="auto", origin="lower", extent=extent,
                       interpolation="none", **kw)
        if i == 0:
            im0 = im
        if colorbar:
            fig.colorbar(im, ax=ax)
        ax.set_title(titles[i] if titles else name)
        ax.set_xlabel(xlabel)
        ax.set_ylabel(ylabel)

    ax = axes.flat[3]
    ok = np.asarray(weights) > 0 if weights is not None \
        else np.abs(port).mean(axis=1) > 0
    if noise_stds is None:
        sig = np.asarray(get_noise_PS(port))  # vectorized over rows
    else:
        sig = noise_stds
    sig = np.where(sig > 0, sig, np.inf)
    dof = max(nbin - nfit, 1)
    rchi2 = (resid ** 2).sum(axis=1) / sig ** 2 / dof
    rchi2 = rchi2[ok & np.isfinite(rchi2)]
    if len(rchi2):
        ax.hist(rchi2, bins=_RCHI2_BINS, histtype="step", color="k")
        lo, hi = rchi2.min(), rchi2.max()
        if lo > 0 and np.log10(hi) - np.log10(lo) > 2:
            ax.semilogx()
        ax.set_xlim(0.9 * lo, 1.1 * hi)
        ax.set_xlabel(r"Red. $\chi^2$")
        ax.set_ylabel(f"# chans. (total = {len(rchi2)})")
        ax.set_title(r"Channel Reduced $\chi^2$")
    else:
        ax.axis("off")
    fig.tight_layout()
    return _finish(fig, show, savefig)


def plot_flux_profile(freqs, fluxes, flux_errs, fit_result, nu_ref,
                      show=True, savefig=None):
    """Flux vs frequency with the fitted power law (reference
    fit_flux_profile plot, pplib.py:448-506)."""
    fig, ax = plt.subplots(figsize=(6, 4))
    ax.errorbar(freqs, fluxes, yerr=flux_errs, fmt="k.", ms=4, lw=0.8)
    grid = np.linspace(min(freqs), max(freqs), 200)
    A = float(fit_result.amp)
    alpha = float(fit_result.alpha)
    ax.plot(grid, A * (grid / nu_ref) ** alpha, "r-", lw=1,
            label=rf"$\alpha$ = {alpha:.2f}")
    ax.set_xlabel("Frequency [MHz]")
    ax.set_ylabel("Flux")
    ax.legend()
    return _finish(fig, show, savefig)


def show_eigenprofiles(eigvec, smooth_eigvec=None, mean_prof=None,
                       smooth_mean_prof=None, show=True, savefig=None,
                       title=None, xlim=(0.0, 1.0), show_snrs=False):
    """Mean profile + significant eigenprofiles, raw and smoothed
    (reference pplib.py:4126-4207; same behaviors): one shared-phase
    column — mean panel first (raw as a faint dotted line under the
    heavy smoothed curve), then one panel per eigenprofile labelled
    1-indexed; x is PHASE in rotations (bin centers), clipped to
    `xlim`; show_snrs annotates each smoothed eigenprofile with the
    Fourier-domain S/N used by the significance veto
    (find_significant_eigvec: spectral power of the smoothed vector
    over the raw vector's scaled noise).

    eigvec / smooth_eigvec: (nbin, ncomp) columns (this framework's
    PCA layout; the reference passes (ncomp, nbin) rows)."""
    from ..ops.noise import get_noise_PS

    eigvec = np.asarray(eigvec)
    ncomp = eigvec.shape[1] if eigvec.ndim == 2 else 0
    nrows = max(ncomp + (1 if mean_prof is not None else 0), 1)
    fig, axes = plt.subplots(nrows, 1, figsize=(7, 2.2 * nrows),
                             sharex=True, squeeze=False)
    irow = 0
    if mean_prof is not None:
        mean_prof = np.asarray(mean_prof)
        ph = (np.arange(len(mean_prof)) + 0.5) / len(mean_prof)
        ax = axes[irow, 0]
        ax.plot(ph, mean_prof, "k:", alpha=0.5)
        if smooth_mean_prof is not None:
            ax.plot(ph, np.asarray(smooth_mean_prof), "k-", lw=2)
        ax.set_ylabel("Mean profile")
        ax.yaxis.set_label_coords(-0.1, 0.5)
        irow += 1
    for icomp in range(ncomp):
        ax = axes[irow, 0]
        ph = (np.arange(eigvec.shape[0]) + 0.5) / eigvec.shape[0]
        ax.plot(ph, eigvec[:, icomp], "k:", alpha=0.5)
        if smooth_eigvec is not None:
            sm = np.asarray(smooth_eigvec)[:, icomp]
            ax.plot(ph, sm, "k-", lw=2)
            if show_snrs:
                # the significance veto's Fourier S/N: smoothed
                # spectral power (DC excluded) over the raw vector's
                # Fourier-scaled noise (reference pplib.py:4168-4174)
                noise = float(get_noise_PS(eigvec[:, icomp])) \
                    * np.sqrt(len(sm) / 2.0)
                if noise > 0.0:  # same guard as the significance veto
                    snr = np.sum(np.abs(np.fft.rfft(sm)[1:]) ** 2) \
                        / noise
                    ax.text(0.9, 0.9, f"S/N = {snr:.0f}", ha="center",
                            va="center", transform=ax.transAxes)
        ax.set_ylabel(f"Eigenprofile {icomp + 1}")
        ax.yaxis.set_label_coords(-0.1, 0.5)
        irow += 1
    for ax in axes[:, 0]:
        ax.set_xlim(xlim)
    axes[-1, 0].set_xlabel("Phase [rot]")
    if title:
        axes[0, 0].set_title(title)
    fig.tight_layout()
    return _finish(fig, show, savefig)


def show_spline_curve_projections(proj, freqs, tck=None, ncoord=None,
                                  show=True, savefig=None, title=None,
                                  weights=None, icoord=None):
    """Projections of the fitted B-spline evolution curve (reference
    pplib.py:3977-4123; same behaviors, two figures):

    - a PAIRWISE grid over every coordinate pair (upper triangle of an
      (ncoord-1) x (ncoord-1) layout, shared "Coordinate" master
      labels), and a coordinate-vs-FREQUENCY column with a shared
      frequency axis;
    - per-channel points carry the fit's structure: marker size maps
      the spline-fit weights onto [5, 15] pt, opacity ramps 0.25 -> 1
      along the channel order, a thin black line connects the data in
      order, the 10x-oversampled spline curve is drawn in green, and
      the knot locations are starred;
    - descending-frequency (negative-bandwidth) data flips the curve
      overlays so they draw in plot order;
    - icoord selects ONE coordinate-vs-frequency panel (no pair grid);
      ncoord limits how many leading coordinates are shown;
    - savefig writes <base>.proj.png and <base>.freq.png like the
      reference.

    Returns (pair_fig_or_None, freq_fig)."""
    from matplotlib.colors import to_rgba

    from ..models.spline import bspline_eval

    proj = np.asarray(proj)
    freqs = np.asarray(freqs)
    nprof, ntot = proj.shape
    if icoord is not None:
        if not 0 <= icoord < ntot:
            raise ValueError(f"0 <= icoord < {ntot}; got {icoord}")
        coords = [icoord]
    else:
        ncoord = ntot if ncoord is None else ncoord
        if not 1 <= ncoord <= ntot:
            raise ValueError(f"1 <= ncoord <= {ntot}; got {ncoord}")
        coords = list(range(ncoord))
    flip = -1 if len(freqs) > 1 and freqs[0] > freqs[-1] else 1
    if tck is not None:
        grid = np.linspace(freqs.min(), freqs.max(), nprof * 10)
        curve = np.atleast_2d(np.asarray(bspline_eval(grid, tck)))
        knot_pos = np.asarray(tck[0])
        knot_vals = np.atleast_2d(np.asarray(bspline_eval(knot_pos,
                                                          tck)))
    # weight-mapped marker sizes on [5, 15] pt, opacity ramp along the
    # channel order (reference pplib.py:4040-4046)
    if weights is None:
        ms = np.full(nprof, 4.0)
    else:
        w = np.asarray(weights, float)
        span = w.max() - w.min()
        ms = 5.0 + 10.0 * (w - w.min()) / (span if span > 0 else 1.0)
    alphas = np.linspace(0.25, 1.0, nprof)
    colors = np.asarray([to_rgba("purple", a) for a in alphas])

    def scatter_pts(ax, x, y):
        ax.scatter(x, y, s=ms ** 2, c=colors, marker="o",
                   linewidths=0.0)

    npair_axis = len(coords) - 1
    fig_pair = None
    if icoord is None and npair_axis >= 1:
        fig_pair, paxes = plt.subplots(
            npair_axis, npair_axis, squeeze=False,
            figsize=(3 * npair_axis + 2, 3 * npair_axis + 2))
        for ix in range(npair_axis):        # x coordinate index
            for iy in range(npair_axis):    # row: y coordinate ix+...
                oy = iy + 1
                ax = paxes[iy, ix]
                if oy <= ix:                # lower triangle: unused
                    ax.axis("off")
                    continue
                scatter_pts(ax, proj[:, ix], proj[:, oy])
                ax.plot(proj[:, ix], proj[:, oy], "k-", lw=1)
                if tck is not None:
                    ax.plot(curve[:, ix], curve[:, oy], "g-", lw=2)
                    ax.plot(knot_vals[:, ix], knot_vals[:, oy], "k*",
                            ms=10)
                if oy == npair_axis:
                    ax.set_xlabel(str(ix + 1))
                else:
                    ax.tick_params(labelbottom=False)
                if ix == 0:
                    ax.set_ylabel(str(oy + 1))
                else:
                    ax.tick_params(labelleft=False)
        fig_pair.supxlabel("Coordinate")
        fig_pair.supylabel("Coordinate")
        if title:
            fig_pair.suptitle(title)

    fig_freq, faxes = plt.subplots(len(coords), 1, sharex=True,
                                   squeeze=False,
                                   figsize=(7, 3 * len(coords) + 1))
    for row, ic in enumerate(coords):
        ax = faxes[row, 0]
        scatter_pts(ax, freqs, proj[:, ic])
        ax.plot(freqs, proj[:, ic], "k-", lw=1)
        if tck is not None:
            ax.plot(grid[::flip], curve[:, ic][::flip], "g-", lw=2)
            ax.plot(knot_pos[::flip], knot_vals[:, ic][::flip], "k*",
                    ms=10)
        ax.set_ylabel(f"Coordinate {ic + 1}")
        ax.yaxis.set_label_coords(-0.1, 0.5)
    faxes[-1, 0].set_xlabel("Frequency [MHz]")
    if title:
        fig_freq.suptitle(title)

    if savefig:
        if fig_pair is not None:
            fig_pair.savefig(f"{savefig}.proj.png", format="png",
                             bbox_inches="tight", dpi=120)
            plt.close(fig_pair)
        fig_freq.savefig(f"{savefig}.freq.png", format="png",
                         bbox_inches="tight", dpi=120)
        plt.close(fig_freq)
    elif show:
        plt.show()
    return fig_pair, fig_freq
