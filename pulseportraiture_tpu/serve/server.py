"""Continuous-batching TOA service: a long-lived serving loop over the
stream executor (ISSUE 8 tentpole; ROADMAP item 2).

Every driver before this PR was one-shot: ``stream_ipta_campaign``
sharded a fixed job list and exited, re-paying executor spin-up, jit
traces, and cold h2d warmup per invocation.  The wideband-TOA pipeline
is embarrassingly batchable across pulsars AND requests, so this
module applies the LLM-serving shape (continuous batching a la
Orca/vLLM) to pulsar timing:

- ONE warm :class:`~..pipeline.stream._StreamExecutor` per host lives
  for the server's lifetime (``service=True``): jit caches, device
  transfer pipelines, the persistent compile cache, and the AOT warmup
  all survive across requests, so steady-state requests never pay a
  cold start;
- concurrent clients :meth:`~ToaServer.submit` archives through a
  bounded :class:`~.queue.AdmissionQueue` (backpressure is LOUD —
  ``ServeRejected`` — never an unbounded host-memory queue);
- the serving loop builds ONE lane per (template, options) pair
  (``make_wideband_lane``; the TemplateModel load amortizes across
  requests) and admits every request's subints into SHARED shape
  buckets: compatible subints from different requests coalesce into
  the same fused dispatch (``batch_coalesce`` telemetry proves it);
- a bucket launches when FULL or when its oldest subint exceeds the
  ``serve_max_wait_ms`` deadline (partial buckets pad to the compiled
  shape class) — heavy traffic fills buckets, light traffic still
  meets latency targets;
- completed TOAs demultiplex back per request, in the request's
  archive order, with the one-shot driver's checkpoint format
  (completion sentinels) as the durability story — per-request
  ``.tim`` output is byte-identical to ``stream_wideband_TOAs``;
- :meth:`~ToaServer.stop` drains gracefully: the queue closes (new
  submissions reject), pending buckets flush, in-flight dispatches
  drain, every outstanding request resolves.

Scope: the wideband campaign configuration (the same option set
``stream_wideband_TOAs`` streams).  Multi-host serving stacks this
per-host loop under a router, exactly as the campaign drivers stack
under ``parallel/multihost.py``.
"""

import os
import threading
import time

import numpy as np

from ..io.tim import write_TOAs
from ..pipeline.stream import (_DONE_PREFIX, _StreamExecutor,
                               _collect_wideband, make_wideband_lane)
from ..telemetry import log, resolve_tracer
from ..utils.bunch import DataBunch
from . import codec
from .queue import AdmissionQueue, ServeRejected, ServeRequest

__all__ = ["ToaServer"]

# Most-recently-used (template, options) lanes a long-lived server
# keeps cached.  Each entry pins a loaded TemplateModel plus its
# instrumental-response cache, so an unbounded cache would grow host
# memory for every distinct template ever served; eviction is safe —
# buckets and in-flight records hold their own lane references, and a
# re-request simply rebuilds the lane (whose key_prefix, and therefore
# bucket keys, are unchanged).
LANE_CACHE_MAX = 32


# Canonical option freezing is shared with the content-addressed
# result cache so the lane key and the cache key can never disagree
# about what an "option change" is.
from .cache import (_freeze, content_key,  # noqa: E402
                    resolve_result_cache)


class ToaServer:
    """A long-lived wideband-TOA serving loop over one warm executor.

    Thread model: ``submit`` is safe from any thread (it only touches
    the admission queue and the tracer); everything executor-facing —
    archive loads, bucket fills, dispatch launches, drains, request
    completion — happens on the single server thread, so the executor
    needs no locking.  Client threads block in
    ``ServeRequest.result()``.

    nsub_batch: the fused-bucket row count (every dispatch pads to a
    multiple of it, so it is also the compiled batch shape class).
    max_wait_ms / queue_depth default to ``config.serve_max_wait_ms`` /
    ``config.serve_queue_depth``.  stream_devices / max_inflight /
    pipeline_depth / telemetry follow the streaming drivers.
    warmup_manifest: a prior run's telemetry trace — every dispatch
    shape it records is AOT-compiled at :meth:`start`
    (``utils/device.warmup_from_manifest``) and marked warm, so the
    serve trace shows zero cold dispatches for manifest shapes;
    warmup_model: template whose portrait shapes the warmup programs
    (defaults to a synthetic smooth profile); warmup_options:
    fit-option overrides forwarded to the warmup pass.

    quality_refit (default config.quality_refit / PPT_QUALITY_REFIT):
    the closed quality loop — an archive whose fitted TOAs trip the
    quality_max_gof / quality_min_snr thresholds gets exactly ONE
    automatic zap-and-refit (ppzap median proposal, in-memory weight
    zap, re-fit through the same warm lane) before its .tim line
    demuxes; per-request .tim content and ordering are unchanged for
    anything that never trips a gate, and a refit that cannot help
    serves the original (or still-tripping zapped) fit LOUDLY.
    zap_nstd overrides the proposal threshold (config.zap_nstd).
    """

    def __init__(self, nsub_batch=64, max_wait_ms=None, queue_depth=None,
                 stream_devices=None, max_inflight=None,
                 pipeline_depth=None, telemetry=None,
                 warmup_manifest=None, warmup_model=None,
                 warmup_options=None, quiet=True, quality_refit=None,
                 quality_max_gof=None, quality_min_snr=None,
                 zap_nstd=None, tenant_quota=None, tenant_weight=None,
                 result_cache=None, cache_dir=None, metrics=None,
                 slo_targets=None):
        from .. import config

        if max_wait_ms is None:
            max_wait_ms = config.serve_max_wait_ms
        if queue_depth is None:
            queue_depth = config.serve_queue_depth
        # quality-gated zap-and-refit loop (ISSUE 12): a request
        # archive whose fitted TOAs trip these thresholds gets exactly
        # one automatic zap-and-refit through the same warm lanes
        # before its .tim demuxes; None reads the config.quality_* /
        # PPT_QUALITY_* knobs
        self.quality_refit = bool(
            config.quality_refit if quality_refit is None
            else quality_refit)
        self.quality_max_gof = float(
            config.quality_max_gof if quality_max_gof is None
            else quality_max_gof)
        self.quality_min_snr = float(
            config.quality_min_snr if quality_min_snr is None
            else quality_min_snr)
        from ..pipeline.zap import resolve_zap_nstd

        self.zap_nstd = resolve_zap_nstd(zap_nstd)
        self.nsub_batch = int(nsub_batch)
        self.max_wait_s = max(0.0, float(max_wait_ms)) / 1e3
        self.quiet = quiet
        self.tracer, self._own_tracer = resolve_tracer(telemetry,
                                                       run="ppserve")
        # content-addressed result cache (ISSUE 17): resolved from the
        # config tri-state (off by default — 'auto' engages only when a
        # cache_dir is set); a submit-time hit bypasses the admission
        # queue entirely and is never billed as a fit
        self.cache = resolve_result_cache(tracer=self.tracer,
                                          cache_dir=cache_dir,
                                          mode=result_cache)
        self._cache_hits = 0
        self._cache_bytes = 0
        # smoothed measured fit throughput (TOAs/s) over completed
        # requests — the backend-aware routing signal (ISSUE 19):
        # an EMA so one odd request can't whipsaw placement, and
        # None until the first real fit (cache hits never count —
        # they say nothing about this host's compute speed)
        self._toa_rate = None
        # live observability plane (ISSUE 20): streaming counters +
        # log-bucket latency histograms (p50/p99 without sample
        # retention) exported over the ``metrics`` transport op, and
        # per-tenant SLO burn-rate tracking when targets are set.
        # None reads config.metrics / config.slo_targets.
        from ..obs.metrics import MetricsRegistry
        from ..obs.slo import SloTracker

        want_metrics = (config.metrics if metrics is None
                        else bool(metrics))
        self._metrics = MetricsRegistry() if want_metrics else None
        targets = (config.slo_targets if slo_targets is None
                   else slo_targets)
        self._slo = SloTracker(targets) if targets else None
        # multi-tenant QoS (ISSUE 13): per-tenant weighted-fair lanes
        # + quotas; None reads config.serve_tenant_quota/_weight
        self.queue = AdmissionQueue(queue_depth,
                                    tenant_quota=tenant_quota,
                                    tenant_weight=tenant_weight)
        self._ex = _StreamExecutor(
            None, [], None, self.nsub_batch, max_inflight=max_inflight,
            prefetch=False, tim_out=None, quiet=quiet,
            stream_devices=stream_devices, tracer=self.tracer,
            pipeline_depth=pipeline_depth, service=True)
        self._ex.on_archive_done = self._archive_done
        self._ex.on_launch = self._launched
        self._lanes = {}      # (modelfile, frozen options) -> lane pair
        self._by_iarch = {}   # executor iarch -> (request, position)
        self._iarch = 0
        # id(request) -> request (admitted, unresolved).  Keyed by
        # OBJECT identity, not name: names are client-chosen labels
        # and two in-flight requests may collide on one — an abort
        # must still fail BOTH loudly, never strand a blocked client
        self._live = {}
        self._thread = None
        self._started = False
        self._stopping = threading.Event()
        self._drain = True
        self._fatal = None
        # quality loop state (server thread only): gated archives
        # queued for zap-and-refit (processed from the serving loop,
        # never from inside an executor drain callback — re-entrant
        # admits would interleave with a mid-fill bucket), and the
        # executor iarchs that ARE refits (their completion finalizes
        # the refit instead of re-entering the gate)
        self._refits_pending = []
        self._refit_iarchs = {}
        self._warmup = (warmup_manifest, warmup_model,
                        dict(warmup_options or {}))

    # ------------------------------------------------------------------
    # client surface
    # ------------------------------------------------------------------

    def submit(self, datafiles, modelfile, tim_out=None, name=None,
               tenant=None, trace_id=None, **options):
        """Enqueue one request (thread-safe).  Raises
        :class:`ServeRejected` when the admission queue is full
        (backpressure), the request's tenant is over its quota, or the
        server is stopping; returns a :class:`ServeRequest` whose
        ``result()`` blocks for the per-request DataBunch.  ``tenant``
        labels the request's weighted-fair QoS lane (None =
        'default').  ``trace_id`` is the distributed-tracing context a
        router minted upstream (None mints one here), stamped into
        every event this request touches."""
        req = ServeRequest(datafiles, modelfile, options=options,
                           tim_out=tim_out, name=name, tenant=tenant,
                           trace_id=trace_id)
        if self._stopping.is_set():
            raise ServeRejected(
                f"server is stopping; request {req.name!r} rejected")
        if self._fatal is not None:
            raise ServeRejected(
                f"server died: {self._fatal!r}; request {req.name!r} "
                "rejected")
        if self.cache is not None and self._cache_try_hit(req):
            return req
        self.queue.submit(req)
        if self.tracer.enabled:
            self.tracer.emit("request_submit", req=req.name,
                             n_archives=len(req.datafiles),
                             tenant=req.tenant,
                             trace_id=req.trace_id)
        return req

    def _cache_try_hit(self, req):
        """Content-addressed lookup at submit time (ISSUE 17).  On a
        hit the request resolves here — the stored ``.tim`` bytes are
        served verbatim (atomic byte copy when the request wants a
        ``.tim``), the request never enters the admission queue, and
        the hit is recorded on the tenant's ledger WITHOUT consuming
        quota or weighted-fair vtime.  Returns True iff the request
        was resolved from the cache.  On a miss the content key is
        stashed on the request so a clean completion populates the
        store without re-hashing."""
        try:
            key = content_key(
                list(req.datafiles) + [req.modelfile], req.options)
        except OSError:
            # unreadable input: fall through to the fit path, which
            # reports the real error through the normal channel
            return False
        req._cache_key = key
        ent = self.cache.get_result(key, req.datafiles)
        if ent is None:
            if self.tracer.enabled:
                self.tracer.emit("cache_miss", req=req.name,
                                 source="server", tenant=req.tenant,
                                 trace_id=req.trace_id)
            return False
        result, entry_path, n_bytes = ent
        if req.tim_out:
            codec.copy_tim_atomic(entry_path, req.tim_out)
        result.tim_out = req.tim_out
        req._cache_hit = True
        req.t_submit = req.t_admit = time.monotonic()
        self.queue.record_hit(req.tenant, len(req.datafiles))
        self._cache_hits += 1
        self._cache_bytes += n_bytes
        if self._metrics is not None:
            self._metrics.inc("cache_hits")
            self._metrics.inc("cache_bytes", n_bytes)
        if self.tracer.enabled:
            self.tracer.emit("request_submit", req=req.name,
                             n_archives=len(req.datafiles),
                             tenant=req.tenant,
                             trace_id=req.trace_id)
            self.tracer.emit("cache_hit", req=req.name, bytes=n_bytes,
                             source="server", tenant=req.tenant,
                             trace_id=req.trace_id)
            self.tracer.counter("cache_hit")
        self._complete(req, result=result)
        return True

    def stats(self):
        """Load snapshot (thread-safe): pending_archives is the
        admission queue's in-ARCHIVES depth (submitted, not yet
        prepared — the backpressure bound), queue_len the queued
        request count, n_live the admitted-but-unresolved requests.
        This is the signal the cross-host router's least-loaded
        placement and the transport ``stat`` op read."""
        from ..tune.capability import capability_summary

        # ONE lock-held read of both queue load fields: reading
        # pending_archives and len(queue) separately can tear against
        # a concurrent submit (ISSUE 20 satellite)
        queue_len, pending = self.queue.load_snapshot()
        return {"pending_archives": pending,
                "queue_len": queue_len,
                "n_live": len(self._live),
                # hit traffic is O(1) and never occupies the executor,
                # so it rides OUTSIDE the load signal above — a
                # hit-heavy host must not look busy to the router
                "cache_hits": self._cache_hits,
                "cache_bytes": self._cache_bytes,
                # backend-aware routing signals (ISSUE 19): the host's
                # capability record (static fields only — a stat
                # handler must not pay probe latency) and the smoothed
                # measured TOAs/s the router's cost model divides by
                "toas_per_s": self._toa_rate,
                "capability": capability_summary()}

    def metrics(self):
        """Live-metrics reply (the ``metrics`` transport op): the
        stat-shaped load snapshot plus the streaming registry export
        (counters, gauges, latency histograms) and the per-tenant SLO
        snapshot.  Process-global h2d counters fold in so the link
        stall fraction rides the same reply.  Histograms use the
        fleet-shared ``obs.metrics.HIST_BOUNDS``, which is what lets a
        router merge replies bucket-wise."""
        from ..obs import metrics as obs_metrics

        queue_len, pending = self.queue.load_snapshot()
        out = {"pending_archives": pending,
               "queue_len": queue_len,
               "n_live": len(self._live),
               "cache_hits": self._cache_hits,
               "cache_bytes": self._cache_bytes,
               "toas_per_s": self._toa_rate,
               "metrics_enabled": self._metrics is not None,
               "metrics": None, "link_stall_frac": None,
               "slo": self._slo.snapshot() if self._slo else None}
        if self._metrics is not None:
            ex = self._metrics.export()
            g = obs_metrics.global_registry().export()
            merged = obs_metrics.merge_exports([ex, g])
            merged["gauges"] = {**g["gauges"], **ex["gauges"]}
            out["metrics"] = merged
            out["link_stall_frac"] = obs_metrics.link_stall_frac(merged)
        return out

    def start(self):
        """Run the optional AOT warmup, then start the serving thread.
        Returns self (usable as ``with ToaServer(...).start() as s:``
        via the context manager)."""
        if self._started:
            raise RuntimeError("ToaServer.start() called twice")
        self._started = True
        manifest, wmodel, wopts = self._warmup
        if manifest:
            from ..utils.device import warmup_from_manifest

            warmed = warmup_from_manifest(
                manifest, modelfile=wmodel, devices=self._ex.devices,
                nsub_batch=self.nsub_batch, tracer=self.tracer,
                quiet=self.quiet, **wopts)
            for shape, idev in warmed:
                # pre-seed the executor's warm set: the first REAL
                # dispatch of a warmed shape is not a cold start, and
                # the trace must say so (ROADMAP item 5's gate).
                # TRUSTED, not verified: warmup_options/warmup_model
                # must match the serving workload (they ride the
                # program cache keys) — a mismatched warmup still
                # marks the shape warm while the first real dispatch
                # pays its own compile.  Cross-check with pptrace's
                # dispatch->dispatched worker gaps if in doubt.
                self._ex._warm.add((shape, idev))
        if self.tracer.enabled:
            self.tracer.emit(
                "serve_start", n_devices=len(self._ex.devices),
                nsub_batch=self.nsub_batch,
                max_wait_ms=round(self.max_wait_s * 1e3, 3),
                queue_depth=self.queue.max_pending)
        log(f"ppserve: serving on {len(self._ex.devices)} device(s), "
            f"bucket {self.nsub_batch} subints / "
            f"{self.max_wait_s * 1e3:.0f} ms deadline, queue depth "
            f"{self.queue.max_pending} archive(s)", quiet=self.quiet,
            tracer=None)
        self._thread = threading.Thread(target=self._loop,
                                        name="ppt-serve", daemon=True)
        self._thread.start()
        return self

    def stop(self, drain=True, timeout=None):
        """Stop serving.  drain=True (graceful): close the queue (new
        submissions reject), serve everything already accepted —
        pending buckets flush, in-flight dispatches drain, every
        outstanding request resolves — then shut the executor down.
        drain=False: abort; outstanding requests fail loudly.  Raises
        the serving loop's error if it died."""
        self._drain = bool(drain)
        self._stopping.set()
        self.queue.close()
        if self._thread is not None:
            self._thread.join(timeout)
        else:
            # never started: nothing admitted; fail anything queued
            self._fail_requests(self.queue.drain(),
                                ServeRejected("server never started"))
        if self.tracer.enabled:
            self.tracer.emit("serve_stop", drained=bool(drain))
        if self._own_tracer:
            self.tracer.close()
        if self._fatal is not None:
            raise self._fatal

    def __enter__(self):
        if not self._started:
            self.start()
        return self

    def __exit__(self, exc_type, exc, tb):
        # on an exception path, don't block on a graceful drain
        self.stop(drain=exc_type is None)
        return False

    # ------------------------------------------------------------------
    # serving loop (single thread owns the executor)
    # ------------------------------------------------------------------

    def _loop(self):
        ex = self._ex
        try:
            while True:
                req = self.queue.get(self._tick())
                if req is not None:
                    self._admit_request(req)
                ex.flush_stale(self.max_wait_s)
                ex._drain_ready()
                self._process_refits()
                if self._stopping.is_set() and (
                        not self._drain or len(self.queue) == 0):
                    break
            if self._drain:
                ex.flush_all()
                ex.drain_all()
                # quality loop: drained archives may have queued
                # refits; each refit admits more work, so flush/drain
                # until the loop is quiescent (bounded — every
                # position refits at most once)
                while True:
                    self._process_refits()
                    if not self._refits_pending and \
                            not self._refit_iarchs:
                        break
                    ex.flush_all()
                    ex.drain_all()
                    # a refit archive can hit the same never-completes-
                    # through-the-drain state as originals (a lane
                    # admitting fewer entries than ok subints) — without
                    # this it would pin _refit_iarchs and spin this
                    # loop forever; assemble_leftover fires the
                    # _archive_done hook, which finalizes the refit
                    for ia in sorted(set(self._refit_iarchs)
                                     & set(self._by_iarch)):
                        ex.assemble_leftover(ia)
                # archives that never completed through the drain
                # (lanes admitting fewer entries than ok subints)
                for ia in sorted(self._by_iarch):
                    ex.assemble_leftover(ia)
                ex._shutdown(wait=True)
            else:
                ex._shutdown(wait=False)
                self._fail_requests(
                    list(self._live.values()) + self.queue.drain(),
                    ServeRejected("server stopped without drain"))
        except BaseException as e:  # the loop must never die silently
            self._fatal = e
            ex._shutdown(wait=False)
            self._fail_requests(
                list(self._live.values()) + self.queue.drain(), e)

    def _tick(self):
        """How long the queue wait may block before the loop must tick
        again: the oldest bucket's remaining deadline, a short poll
        while dispatches are in flight, a longer idle poll otherwise."""
        if self._stopping.is_set() or self._refits_pending:
            return 0.0
        age = self._ex.oldest_bucket_age()
        if age is not None:
            return max(0.0, min(self.max_wait_s - age, 0.05))
        if any(self._ex.in_flight):
            return 0.002
        return 0.05

    def _lane_for(self, req):
        key = (os.path.abspath(req.modelfile),
               tuple(sorted((k, _freeze(v))
                            for k, v in req.options.items())))
        ent = self._lanes.pop(key, None)
        if ent is None:
            # one lane per (template, options): the model load
            # amortizes across every request that reuses it, and the
            # key_prefix namespaces bucket keys so same-layout buckets
            # of DIFFERENT templates can never share a dispatch while
            # same-(template, options) requests always can
            lane, loader = make_wideband_lane(
                req.modelfile, nsub_batch=self.nsub_batch,
                quiet=self.quiet, tracer=self.tracer,
                key_prefix=(key,), **req.options)
            ent = (lane, loader)
        # re-insert = move to most-recent; evict the oldest beyond the
        # cache bound (dicts iterate in insertion order)
        self._lanes[key] = ent
        while len(self._lanes) > LANE_CACHE_MAX:
            self._lanes.pop(next(iter(self._lanes)))
        return ent

    def _admit_request(self, req):
        req.t_admit = time.monotonic()
        try:
            lane, loader = self._lane_for(req)
        except Exception as e:
            # a bad modelfile/option set fails ITS request, not the
            # server
            self.queue.release(len(req.datafiles), tenant=req.tenant)
            self._complete(req, error=e)
            return
        self._live[id(req)] = req
        ex = self._ex
        from ..pipeline.toas import _iter_archives

        # archive IO runs ahead of admission on prefetch threads (the
        # same overlap discipline as the one-shot driver) — the
        # serving thread buckets archive N while N+1..N+4 load
        for pos, (f, d) in enumerate(
                _iter_archives(req.datafiles, loader, prefetch=True)):
            skip = None
            if isinstance(d, Exception):
                skip = str(d)
            if skip is None:
                ok = np.asarray(d.ok_isubs, int)
                if d.nsub == 0 or len(ok) == 0:
                    skip = "no subints to fit"
            if skip is not None:
                self.tracer.emit("archive_skip", datafile=f,
                                 reason=skip)
                self.tracer.counter("archives_skipped")
                log(f"Skipping {f}: {skip}", level="warn", tracer=None)
                req.n_skipped += 1
                self.queue.release(1, tenant=req.tenant)
                continue
            ia = self._iarch
            self._iarch += 1
            self._by_iarch[ia] = (req, pos)
            # admit may block on a full device queue; the drains it
            # runs fire _archive_done callbacks on this same thread
            if ex.admit(ia, f, d, ok, lane=lane) is None:
                del self._by_iarch[ia]
                req.n_skipped += 1
            self.queue.release(1, tenant=req.tenant)
            # keep latency honest while a long request streams in
            ex.flush_stale(self.max_wait_s)
            ex._drain_ready()
        req.all_admitted = True
        self._maybe_complete(req)

    # -- executor hooks (server thread) --------------------------------

    def _launched(self, seq, owners, pad):
        if self._metrics is not None:
            self._metrics.inc("dispatches")
            self._metrics.inc("rows_dispatched", len(owners))
        if not self.tracer.enabled:
            return
        members = {self._by_iarch[ia][0] for ia, _ in owners
                   if ia in self._by_iarch}
        names = {r.name for r in members}
        self.tracer.emit("batch_coalesce", seq=seq,
                         n_requests=len(names),
                         requests=sorted(names), rows=len(owners),
                         pad=int(pad),
                         # request-membership by trace context: the
                         # field pptrace merge joins dispatches on
                         trace_ids=sorted({r.trace_id
                                           for r in members}))

    def _archive_done(self, iarch, m, out):
        ent = self._by_iarch.pop(iarch, None)
        if ent is None:
            return
        req, pos = ent
        self._ex.forget(iarch)  # keep the warm executor O(live work)
        rec = self._refit_iarchs.pop(iarch, None)
        if rec is not None:
            self._finish_refit(rec, m, out)
            return
        if (self.quality_refit and pos not in req.refit_pos
                and self._gate_trips(out)):
            # hold this position open (the request cannot complete
            # until the refit resolves — demux order is unchanged) and
            # queue exactly one zap-and-refit; processed from the
            # serving loop, NOT here — this hook can run inside an
            # executor drain that an admit triggered, and a re-entrant
            # admit would interleave with a mid-fill bucket
            req.refit_pos.add(pos)
            self._refits_pending.append(dict(
                req=req, pos=pos, datafile=m.datafile,
                gof_before=self._gof_worst(out), meta=m, out=out))
            return
        req.meta[pos] = m
        req.assembled[pos] = out
        self._maybe_complete(req)

    # -- quality-gated zap-and-refit (ISSUE 12) ------------------------

    def _gof_worst(self, out):
        """Worst (largest finite) per-TOA goodness-of-fit of one
        archive assembly — the quality rollup the gate reads."""
        gofs = [t.flags.get("gof") for t in out[0]]
        gofs = [g for g in gofs if g is not None and np.isfinite(g)]
        return max(gofs) if gofs else None

    def _gate_trips(self, out):
        """True when any TOA of the assembly trips the configured
        thresholds (gof above quality_max_gof, or — when the S/N gate
        is enabled — snr below quality_min_snr)."""
        for t in out[0]:
            gof = t.flags.get("gof")
            if gof is not None and np.isfinite(gof) \
                    and gof > self.quality_max_gof:
                return True
            if self.quality_min_snr > 0.0:
                snr = t.flags.get("snr")
                if snr is not None and np.isfinite(snr) \
                        and snr < self.quality_min_snr:
                    return True
        return False

    def _fallback_refit(self, rec, n_channels, reason):
        """A refit that cannot run (no channels to zap, empty archive,
        proposal error): serve the ORIGINAL result, loudly."""
        req, pos = rec["req"], rec["pos"]
        if pos in req.assembled:
            # the refit resolved through a drain callback before the
            # failure surfaced (admit can complete an archive
            # synchronously) — its result already demuxed; do not
            # overwrite it with the original
            return
        log(f"quality refit of {rec['datafile']} (request "
            f"{req.name!r}) not possible: {reason}; serving the "
            "original fit", level="warn", tracer=None)
        if self.tracer.enabled:
            self.tracer.emit(
                "refit", req=req.name, datafile=rec["datafile"],
                n_channels=int(n_channels),
                gof_before=rec["gof_before"],
                gof_after=rec["gof_before"], improved=False)
        req.meta[pos] = rec["meta"]
        req.assembled[pos] = rec["out"]
        self._maybe_complete(req)

    def _process_refits(self):
        """Run queued zap-and-refits (server thread, between executor
        drains): propose zaps with the ppzap median algorithm on the
        decoded load, apply them as an in-memory weight zap
        (quality.zap_bunch — bit-identical to loading an offline-
        zapped archive), and re-admit the archive through the SAME
        warm lane the original fit used.  Exactly one refit per
        archive position; failures fall back to the original result,
        loudly."""
        from ..io.psrfits import load_data
        from ..pipeline.zap import get_zap_channels, zap_bunch

        while self._refits_pending:
            rec = self._refits_pending.pop(0)
            req, pos = rec["req"], rec["pos"]
            f = rec["datafile"]
            ia = None
            try:
                lane, loader = self._lane_for(req)
                # the proposal loads DECODED with the ppzap option set
                # (the median algorithm needs host noise levels; the
                # stats themselves follow the zap_device tri-state —
                # one batched dispatch on the device lane)
                d_prop = load_data(
                    f, dedisperse=False, dededisperse=True,
                    tscrunch=req.options.get("tscrunch", False),
                    pscrunch=True, quiet=True)
                # rows come back indexed by true subint number — the
                # zap_bunch format directly
                full = get_zap_channels(d_prop, nstd=self.zap_nstd,
                                        tracer=self.tracer)
                n_channels = sum(len(z) for z in full)
                if n_channels == 0:
                    self._fallback_refit(
                        rec, 0, "the median algorithm flagged no "
                        "channels (contamination is not "
                        "noise-level-separable)")
                    continue
                d = zap_bunch(loader(f), full)
                ok = np.asarray(d.ok_isubs, int)
                if d.nsub == 0 or len(ok) == 0:
                    self._fallback_refit(
                        rec, n_channels,
                        "zapping left no fittable subints")
                    continue
                if self.tracer.enabled:
                    self.tracer.emit("zap_apply", datafile=f,
                                     n_channels=int(n_channels))
                rec["n_channels"] = n_channels
                ia = self._iarch
                self._iarch += 1
                self._by_iarch[ia] = (req, pos)
                self._refit_iarchs[ia] = rec
                if self._ex.admit(ia, f, d, ok, lane=lane) is None:
                    self._by_iarch.pop(ia, None)
                    self._refit_iarchs.pop(ia, None)
                    self._fallback_refit(
                        rec, n_channels,
                        "the lane skipped the zapped archive")
                    continue
            except Exception as e:
                if ia is not None:
                    # a failed admit must not leave the registration
                    # behind: the drain loop would wait on it forever,
                    # and a partially-enqueued fit's late completion
                    # must find nothing to resolve
                    self._by_iarch.pop(ia, None)
                    self._refit_iarchs.pop(ia, None)
                self._fallback_refit(rec, rec.get("n_channels", 0),
                                     f"{type(e).__name__}: {e}")

    def _finish_refit(self, rec, m, out):
        """A refit's fit completed: record the before/after quality,
        warn loudly when the archive STILL trips the gate (the bounded
        loop never refits twice), and demux the zapped fit."""
        req, pos = rec["req"], rec["pos"]
        gof_after = self._gof_worst(out)
        before = rec["gof_before"]
        improved = (gof_after is not None and before is not None
                    and gof_after < before)
        if self.tracer.enabled:
            self.tracer.emit(
                "refit", req=req.name, datafile=rec["datafile"],
                n_channels=int(rec.get("n_channels", 0)),
                gof_before=before, gof_after=gof_after,
                improved=bool(improved))
        if self._gate_trips(out):
            log(f"quality refit of {rec['datafile']} (request "
                f"{req.name!r}) still trips the gate after zapping "
                f"{rec.get('n_channels', 0)} channel(s) "
                f"(red-chi^2 {before} -> {gof_after}); serving the "
                "zapped fit — no further refits (the loop is bounded "
                "to one pass)", level="warn", tracer=None)
        req.meta[pos] = m
        req.assembled[pos] = out
        self._maybe_complete(req)

    # -- request completion --------------------------------------------

    def _maybe_complete(self, req):
        if not req.all_admitted:
            return
        if len(req.assembled) + req.n_skipped < len(req.datafiles):
            return
        try:
            positions = sorted(req.assembled)
            meta = [req.meta[p] for p in positions]
            assembled = {m.iarch: req.assembled[p]
                         for p, m in zip(positions, meta)}
            (TOA_list, order, DM0s, means,
             errs) = _collect_wideband(meta, assembled)
            if req.tim_out:
                # the one-shot checkpoint format, in the REQUEST's
                # archive order: truncate, then per-archive TOA lines +
                # completion sentinel — byte-identical to
                # stream_wideband_TOAs(tim_out=...)
                open(req.tim_out, "w").close()
                for m in meta:
                    write_TOAs(assembled[m.iarch][0],
                               outfile=req.tim_out, append=True)
                    with open(req.tim_out, "a") as fh:
                        fh.write(_DONE_PREFIX
                                 + os.path.abspath(m.datafile) + "\n")
            result = DataBunch(
                TOA_list=TOA_list, order=order, DM0s=DM0s,
                DeltaDM_means=means, DeltaDM_errs=errs,
                tim_out=req.tim_out, n_skipped=req.n_skipped)
            self._complete(req, result=result)
        except Exception as e:
            self._complete(req, error=e)

    def _complete(self, req, result=None, error=None):
        if (self.cache is not None and result is not None
                and getattr(req, "_cache_key", None)
                and not getattr(req, "_cache_hit", False)):
            # populate on request_done: a clean fresh fit lands in the
            # store under the key hashed at submit (put_result refuses
            # partial/recovered results itself)
            stored = self.cache.put_result(req._cache_key, result)
            if stored and self.tracer.enabled:
                self.tracer.emit("cache_store", key=req._cache_key,
                                 bytes=stored)
        req._result = result
        req._error = error
        req.t_done = time.monotonic()
        self._live.pop(id(req), None)
        if (result is not None and error is None
                and not getattr(req, "_cache_hit", False)
                and result.TOA_list):
            # measured-throughput EMA (the stat wire's toas_per_s):
            # admission->done wall of a REAL fit; alpha 0.3 smooths
            # over bucket-shape variance without going stale
            t_adm = req.t_admit if req.t_admit is not None \
                else req.t_submit
            wall = req.t_done - (t_adm if t_adm is not None
                                 else req.t_done)
            if wall > 0:
                rate = len(result.TOA_list) / wall
                self._toa_rate = (rate if self._toa_rate is None
                                  else 0.7 * self._toa_rate
                                  + 0.3 * rate)
        t_sub = req.t_submit if req.t_submit is not None \
            else req.t_done
        t_adm = req.t_admit if req.t_admit is not None \
            else req.t_done
        wall_s = req.t_done - t_sub
        queue_s = t_adm - t_sub
        if self._metrics is not None:
            self._metrics.inc("requests_total")
            if error is not None:
                self._metrics.inc("requests_failed")
            if result is not None:
                self._metrics.inc("toas_total",
                                  len(result.TOA_list or ()))
            self._metrics.observe("request_latency_s", wall_s)
            self._metrics.observe("queue_wait_s", queue_s)
        if self._slo is not None:
            # an errored request burns budget like an infinitely slow
            # one: failures violate a latency objective by definition
            breach = self._slo.observe(
                getattr(req, "tenant", None) or "default",
                wall_s if error is None else float("inf"))
            if breach is not None and self.tracer.enabled:
                self.tracer.emit("slo_breach", source="server",
                                 **breach)
        if self.tracer.enabled:
            self.tracer.emit(
                "request_done", req=req.name,
                n_toas=len(result.TOA_list) if result else 0,
                n_archives=len(result.order) if result else 0,
                wall_s=round(wall_s, 6),
                queue_s=round(queue_s, 6),
                error=str(error) if error else None,
                tenant=getattr(req, "tenant", None),
                trace_id=req.trace_id)
        req._event.set()

    def _fail_requests(self, requests, error):
        for req in requests:
            if not req.done():
                self._complete(req, error=error)
