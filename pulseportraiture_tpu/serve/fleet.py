"""Elastic fleet membership + health state machine (ISSUE 13
tentpole; ROADMAP item 1).

The R13 router sharded requests across a STATIC host list and treated
an unreachable ``stat`` as "infinite load this round" — good enough
for a lab fleet, fatal for a production one: a host that restarts
mid-sweep strands its in-flight archives, a hung host's probe blocks
every placement pass behind the socket timeout, and there is no way to
grow or shrink the fleet without restarting the router.  This module
is the membership layer underneath :class:`~.router.ToaRouter`:

- **Per-host health state machine** —
  ``JOINING -> HEALTHY -> SUSPECT -> DEAD -> REJOINED``:

  ============  =========================================  ==========
  state         meaning                                    placeable
  ============  =========================================  ==========
  JOINING       registered, no successful probe yet        no
  HEALTHY       probes + submits succeeding                yes
  SUSPECT       one probe timeout / transport error        yes
  DEAD          a second consecutive failure               no
  REJOINED      a DEAD host probed OK again (one           no
                more success confirms -> HEALTHY)
  ============  =========================================  ==========

  Success anywhere (probe or submit) resets the failure count:
  SUSPECT recovers to HEALTHY, DEAD steps to REJOINED, REJOINED
  confirms to HEALTHY.  Every edge emits a loud ``fleet_transition``
  telemetry event and a stderr warning for the degrading edges.

- **Bounded probes with cached loads** (the probe-deadline fix):
  every placement pass refreshes loads through :meth:`Fleet.probe_all`
  — each host's ``stat`` runs on its own daemon probe thread and the
  caller waits at most ``config.router_probe_ms``.  While a probe is
  outstanding the cached last-known load is used, so one hung host can
  never delay a placement pass; a probe that exceeds the deadline
  feeds the SUSPECT transition instead of blocking submit (and its
  eventual completion, success or failure, updates the machine).

- **Dynamic membership**: :meth:`Fleet.add` / :meth:`Fleet.remove` at
  runtime (``ToaRouter.add_host``/``remove_host``), and
  :class:`FleetFileWatcher` polls a ``--fleet-file`` (one host:port
  per line) and reconciles the fleet against it, so operators
  join/leave hosts by editing a file.  String endpoints keep their
  address as a re-dial factory: a DEAD socket host whose connection
  was poisoned gets a FRESH transport on its next probe, which is what
  makes re-registration (DEAD -> REJOINED -> HEALTHY) actually work.

The router layers failover on top (serve/router.py): a DEAD
transition with requests in flight triggers exactly-once re-placement
using the durable-``.tim`` property (serve/codec.py).
"""

import threading
import time

from ..telemetry import NULL_TRACER, log

__all__ = ["JOINING", "HEALTHY", "SUSPECT", "DEAD", "REJOINED",
           "PLACEABLE_STATES", "FleetMember", "Fleet",
           "FleetFileWatcher"]

JOINING = "JOINING"
HEALTHY = "HEALTHY"
SUSPECT = "SUSPECT"
DEAD = "DEAD"
REJOINED = "REJOINED"
# placement draws ONLY from these: JOINING/REJOINED hosts are still
# being vetted (their next successful probe promotes them), DEAD hosts
# took work down with them once already
PLACEABLE_STATES = frozenset({HEALTHY, SUSPECT})

# A DEAD endpoint is re-probed at most this often — frequent enough to
# notice a restart within a couple of placement passes, sparse enough
# not to hammer a host that is gone for good.
DEAD_REPROBE_S = 1.0


class _Probe:
    """One in-flight stat probe: the waitable completion event plus
    the timed-out latch (a probe past the deadline feeds SUSPECT
    exactly once; its eventual completion still updates the machine)."""

    def __init__(self):
        self.t0 = time.monotonic()
        self.done = threading.Event()
        self.timed_out = False


class FleetMember:
    """One endpoint: transport + health state + the router-side load
    bookkeeping placement reads."""

    def __init__(self, transport, index, factory=None):
        self.transport = transport
        self.index = index
        self.label = getattr(transport, "label", f"host{index}")
        # re-dial hook: string endpoints re-register through a fresh
        # SocketTransport when a DEAD (poisoned) connection probes
        self.factory = factory
        self.state = JOINING
        self.outstanding = 0   # archives submitted, result not collected
        self.n_requests = 0    # requests ever placed here
        self.n_archives = 0    # archives ever placed here
        self.cached_pending = None  # last stat()['pending_archives']
        # backend-aware routing signals (ISSUE 19), refreshed by every
        # successful probe: the host's smoothed measured fit
        # throughput (None until its first real fit — the router's
        # cost model then treats it as fleet-fast, i.e. degrades to
        # least-loaded) and its static capability record
        self.toas_per_s = None
        self.capability = None
        self._probe = None
        self._last_probe_t = 0.0

    def load(self):
        """Cached load: this router's outstanding archives plus the
        host's last-known admission-queue depth (other clients'
        submits are visible there).  Never blocks — freshness is
        probe_all's job."""
        if self.cached_pending is None:
            return self.outstanding
        return self.outstanding + self.cached_pending


class Fleet:
    """Membership registry + health state machine over N endpoints.

    ``on_dead(member)`` fires (outside the fleet lock) whenever a
    member transitions to DEAD — the router hangs its in-flight
    failover there.  ``probe_ms`` bounds every placement pass's load
    refresh (None = ``config.router_probe_ms``)."""

    def __init__(self, tracer=None, probe_ms=None, on_dead=None,
                 quiet=True):
        from .. import config

        self.tracer = tracer if tracer is not None else NULL_TRACER
        if probe_ms is None:
            probe_ms = config.router_probe_ms
        self.probe_s = max(1e-3, float(probe_ms)) / 1e3
        self.on_dead = on_dead
        self.quiet = quiet
        self._lock = threading.Lock()
        self._members = {}     # label -> FleetMember (insertion order)
        self._next_index = 0

    # ------------------------------------------------------------------
    # membership
    # ------------------------------------------------------------------

    def add(self, transport_or_address, label=None):
        """Register one endpoint (JOINING; the next probe promotes a
        reachable host to HEALTHY).  Strings open a SocketTransport
        now (loud TransportError if unreachable — callers that want
        lazy joins, e.g. the fleet-file watcher, catch and retry) and
        keep the address as the re-dial factory."""
        factory = None
        if isinstance(transport_or_address, str):
            from .transport import SocketTransport

            address = transport_or_address
            factory = lambda a=address: SocketTransport(a)  # noqa: E731
            transport = factory()
        else:
            transport = transport_or_address
        with self._lock:
            index = self._next_index
            member = FleetMember(transport, index, factory=factory)
            if label is not None:
                member.label = str(label)
            if member.label in self._members:
                try:
                    if factory is not None:
                        transport.close()
                except Exception:
                    pass
                raise ValueError(
                    f"fleet: duplicate host endpoint {member.label!r}")
            self._next_index += 1
            self._members[member.label] = member
        self._emit(member, None, JOINING, "join")
        return member

    def remove(self, label):
        """Administrative leave: the member stops receiving placements
        immediately; requests already in flight on it keep collecting
        through its transport (a graceful drain, not a kill).  Returns
        the removed member (None when unknown)."""
        with self._lock:
            member = self._members.pop(str(label), None)
        if member is not None:
            self._emit(member, member.state, "LEFT", "removed")
        return member

    def members(self):
        with self._lock:
            return list(self._members.values())

    def get(self, label):
        with self._lock:
            return self._members.get(str(label))

    def snapshot(self):
        """{label: state} — what stats()/tests read."""
        with self._lock:
            return {m.label: m.state for m in self._members.values()}

    # ------------------------------------------------------------------
    # state machine
    # ------------------------------------------------------------------

    def _emit(self, member, old, new, reason):
        if self.tracer.enabled:
            self.tracer.emit("fleet_transition", host=member.label,
                             from_state=old, to_state=new,
                             reason=str(reason))
        level = "warn" if new in (SUSPECT, DEAD) else "info"
        log(f"fleet: {member.label} {old or '-'} -> {new} ({reason})",
            quiet=self.quiet, level=level, tracer=None)

    def record_ok(self, member, pending=None, toas_per_s=None,
                  capability=None):
        """A probe or submit succeeded: refresh the cached load (and
        the throughput/capability signals a stat probe carries) and
        advance the recovery edges (JOINING/SUSPECT -> HEALTHY, DEAD
        -> REJOINED, REJOINED -> HEALTHY)."""
        with self._lock:
            if self._members.get(member.label) is not member:
                return  # removed while the probe was in flight
            if pending is not None:
                member.cached_pending = int(pending)
            if toas_per_s is not None:
                member.toas_per_s = float(toas_per_s)
            if capability is not None:
                member.capability = capability
            old = member.state
            if old in (JOINING, SUSPECT):
                member.state = HEALTHY
            elif old == DEAD:
                member.state = REJOINED
            elif old == REJOINED:
                member.state = HEALTHY
            new = member.state
        if new != old:
            self._emit(member, old, new,
                       "probe ok" if pending is not None
                       else "submit ok")

    def record_error(self, member, reason):
        """A probe timed out / a transport call failed: degrade
        (HEALTHY -> SUSPECT, SUSPECT/REJOINED -> DEAD).  JOINING stays
        JOINING (it never served — it simply remains unvetted and is
        re-probed), DEAD stays DEAD.  A DEAD transition fires the
        router's failover callback."""
        with self._lock:
            if self._members.get(member.label) is not member:
                return
            old = member.state
            if old == HEALTHY:
                member.state = SUSPECT
            elif old in (SUSPECT, REJOINED):
                member.state = DEAD
            new = member.state
        if new != old:
            self._emit(member, old, new, reason)
        if new == DEAD and old != DEAD and self.on_dead is not None:
            self.on_dead(member)

    # ------------------------------------------------------------------
    # bounded probes
    # ------------------------------------------------------------------

    def _probe_worker(self, member, probe):
        try:
            from .transport import TransportError

            try:
                st = member.transport.stat()
            except TransportError:
                if member.factory is None:
                    raise
                # re-registration: a poisoned/refused connection with a
                # known address gets a fresh dial — this is how a
                # restarted ppserve --listen host comes back
                fresh = member.factory()
                old_t, member.transport = member.transport, fresh
                try:
                    old_t.close()
                except Exception:
                    pass
                st = fresh.stat()
            self.record_ok(member, pending=st["pending_archives"],
                           toas_per_s=st.get("toas_per_s"),
                           capability=st.get("capability"))
        except Exception as e:
            # one probe EPISODE charges one strike: if the deadline
            # already fed SUSPECT for this probe (_probe_timeout), its
            # eventual failure must not count a second time — a single
            # stall-then-error blip would otherwise walk a HEALTHY
            # host straight to DEAD and fail over all its work
            if not probe.timed_out:
                self.record_error(member, f"probe failed: {e}")
        finally:
            probe.done.set()

    def _ensure_probe(self, member):
        """Start a probe unless one is already outstanding; returns
        (probe, fresh)."""
        with self._lock:
            probe = member._probe
            if probe is not None and not probe.done.is_set():
                return probe, False
            if member.state == DEAD and \
                    time.monotonic() - member._last_probe_t \
                    < DEAD_REPROBE_S:
                return probe, False  # throttle dead-host re-dials
            probe = member._probe = _Probe()
            member._last_probe_t = probe.t0
        threading.Thread(target=self._probe_worker,
                         args=(member, probe),
                         name=f"ppt-probe-{member.label}",
                         daemon=True).start()
        return probe, True

    def _probe_timeout(self, member, probe):
        """Mark one probe as past its deadline (once): the SUSPECT
        feed.  The straggling probe keeps running — its eventual
        result still lands in the machine."""
        if probe is None or probe.timed_out or probe.done.is_set():
            return
        probe.timed_out = True
        self.record_error(
            member, f"stat probe exceeded "
                    f"{self.probe_s * 1e3:.0f} ms "
                    "(config.router_probe_ms)")

    def probe_all(self, timeout_s=None):
        """Refresh every member's load under ONE shared deadline and
        return ``{member: load}`` for the placement-eligible
        (HEALTHY/SUSPECT) members.  Hosts with an outstanding probe
        contribute their cached last-known load immediately; a probe
        that exceeds the deadline feeds SUSPECT instead of blocking
        the caller."""
        if timeout_s is None:
            timeout_s = self.probe_s
        started = [(m, *self._ensure_probe(m)) for m in self.members()]
        deadline = time.monotonic() + timeout_s
        for member, probe, fresh in started:
            if probe is None:
                continue
            left = deadline - time.monotonic()
            if not (probe.done.is_set()
                    or (left > 0 and probe.done.wait(left))):
                self._probe_timeout(member, probe)
        return {m: m.load() for m in self.members()
                if m.state in PLACEABLE_STATES}

    def close(self):
        """Close every member transport (idempotent per transport)."""
        for m in self.members():
            try:
                m.transport.close()
            except Exception:
                pass


class FleetFileWatcher(threading.Thread):
    """Reconcile a router's fleet against a watched host list.

    The file holds one ``host:port`` per line (blank lines and ``#``
    comments ignored).  Every ``poll_s`` the watcher re-reads it when
    its mtime moved and add_host/remove_host's the router to match —
    only endpoints the watcher itself added are ever removed, so a
    fleet mixed from --hosts and --fleet-file never loses its static
    members.  Unreachable new entries warn and retry on the next poll
    (a host listed before it finished booting simply joins late)."""

    def __init__(self, router, path, poll_s=1.0, quiet=True):
        super().__init__(name="ppt-fleet-file", daemon=True)
        self.router = router
        self.path = str(path)
        self.poll_s = max(0.05, float(poll_s))
        self.quiet = quiet
        self._stop = threading.Event()
        self._mtime = None
        self._managed = set()   # labels this watcher added
        self._warned = set()

    def parse(self):
        """Read the fleet file -> ordered list of host:port strings
        (strictly validated; a malformed line is a loud warning, not a
        silent fleet shrink)."""
        from .. import config

        hosts = []
        try:
            with open(self.path) as fh:
                lines = fh.readlines()
        except OSError as e:
            log(f"fleet-file {self.path}: unreadable ({e})",
                quiet=False, level="warn", tracer=None)
            return None
        for lineno, line in enumerate(lines, 1):
            s = line.strip()
            if not s or s.startswith("#"):
                continue
            try:
                config.parse_hostport(s)
            except ValueError as e:
                log(f"fleet-file {self.path}:{lineno}: {e} — line "
                    "ignored", quiet=False, level="warn", tracer=None)
                continue
            if s not in hosts:
                hosts.append(s)
        return hosts

    def resync(self):
        """One reconciliation pass (also called directly by tests)."""
        from .transport import TransportError

        hosts = self.parse()
        if hosts is None:
            return
        current = set(self.router.host_labels())
        for addr in hosts:
            if addr in current:
                continue
            try:
                self.router.add_host(addr)
                self._managed.add(addr)
                self._warned.discard(addr)
            except (TransportError, ValueError) as e:
                if addr not in self._warned:
                    self._warned.add(addr)
                    log(f"fleet-file: cannot join {addr} yet ({e}); "
                        "will retry", quiet=self.quiet, level="warn",
                        tracer=None)
        wanted = set(hosts)
        for label in sorted(self._managed - wanted):
            self._managed.discard(label)
            if label in current:
                self.router.remove_host(label)

    def run(self):
        # initial sync happens immediately, then on mtime changes
        self.resync()
        while not self._stop.wait(self.poll_s):
            try:
                mtime = None
                try:
                    import os

                    mtime = os.path.getmtime(self.path)
                except OSError:
                    pass
                if mtime != self._mtime:
                    self._mtime = mtime
                    self.resync()
                else:
                    # even without an edit, retry endpoints that were
                    # unreachable on the last pass
                    if self._warned:
                        self.resync()
            except Exception as e:  # the watcher must never die
                log(f"fleet-file watcher: {e}", quiet=False,
                    level="warn", tracer=None)

    def stop(self):
        self._stop.set()
