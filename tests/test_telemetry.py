"""Campaign telemetry (ISSUE 5): trace round-trip and schema, counter
consistency with the executor's returned accounting, disabled-mode
zero-output, byte-identity of campaign output with telemetry on/off
across device counts, the pptrace report, the PPT_TELEMETRY /
unknown-PPT_* env hooks, and the optional per-TOA quality flags.

Shapes are deliberately tiny (8 chan x 64 bin, 3 archives x 2 subints)
and the traced 8-device campaign runs ONCE per module — tier-1 runs
close to its time cap."""

import json
import os

import pytest

from pulseportraiture_tpu import config, telemetry
from pulseportraiture_tpu.io import write_gmodel
from pulseportraiture_tpu.pipeline import GetTOAs, stream_wideband_TOAs
from pulseportraiture_tpu.synth import default_test_model, make_fake_pulsar
from pulseportraiture_tpu.utils.mjd import MJD

PAR = {"PSR": "J1744-1134", "RAJ": "17:44:29.4", "DECJ": "-11:34:54.6",
       "P0": 0.004074, "PEPOCH": 55000.0, "DM": 3.139}


@pytest.fixture(scope="module")
def campaign(tmp_path_factory):
    root = tmp_path_factory.mktemp("telemetry")
    model = default_test_model(1500.0)
    gmodel = str(root / "model.gmodel")
    write_gmodel(model, gmodel, quiet=True)
    files = []
    for i in range(3):
        path = str(root / f"ep{i}.fits")
        make_fake_pulsar(model, PAR, outfile=path, nsub=2, nchan=8,
                         nbin=64, nu0=1500.0, bw=400.0, tsub=60.0,
                         phase=0.03 * i, dDM=1e-4 * i,
                         start_MJD=MJD(55900 + 5 * i, 0.1),
                         noise_stds=0.05, dedispersed=False, quiet=True,
                         rng=700 + i)
        files.append(path)
    return files, gmodel


@pytest.fixture(scope="module")
def traced_run(campaign, tmp_path_factory):
    """ONE 8-device streaming run with telemetry on (plus the 1-device
    telemetry-off reference) shared by the round-trip / report /
    byte-identity tests below."""
    files, gmodel = campaign
    root = tmp_path_factory.mktemp("traced")
    trace = str(root / "trace.jsonl")
    tim8 = str(root / "d8.tim")
    tim1 = str(root / "d1.tim")
    res1 = stream_wideband_TOAs(files, gmodel, nsub_batch=2,
                                stream_devices=1, tim_out=tim1,
                                quiet=True)  # telemetry OFF
    res8 = stream_wideband_TOAs(files, gmodel, nsub_batch=2,
                                stream_devices=8, tim_out=tim8,
                                telemetry=trace, quiet=True)
    return dict(files=files, trace=trace, tim1=tim1, tim8=tim8,
                res1=res1, res8=res8)


def test_trace_round_trip_schema_and_ordering(traced_run):
    """Manifest first (versioned, self-describing), counters last,
    every event of a known type with its required fields, event
    timeline consistent (dispatch before its drain)."""
    manifest, events = telemetry.validate_trace(traced_run["trace"])
    assert manifest["schema"] == telemetry.TRACE_SCHEMA_VERSION
    assert manifest["run"] == "stream_wideband_TOAs"
    assert manifest["backend"] == "cpu"
    assert len(manifest["devices"]) == 8
    # config snapshot names every env_overrides()-controlled knob
    for key in ("stream_devices", "stream_max_inflight",
                "cross_spectrum_dtype", "dft_precision"):
        assert key in manifest["config"], key
    assert events[-1]["type"] == "counters"
    disp = {e["seq"]: e for e in events if e["type"] == "dispatch"}
    drain = {e["seq"]: e for e in events if e["type"] == "drain"}
    assert set(disp) == set(drain)  # every dispatch drained
    for seq, d in drain.items():
        assert d["t"] >= disp[seq]["t"]
        assert d["device"] == disp[seq]["device"]
    # per-archive lifecycle: 3 prepares, 3 assemblies, 3 in-order
    # checkpoint flushes
    for etype in ("archive_prepare", "archive_done", "ckpt_flush"):
        assert sum(e["type"] == etype for e in events) == 3, etype


def test_trace_counters_match_executor_accounting(traced_run):
    """The acceptance criterion: per-device bucket counts sum to the
    executor's nfit and the max recorded queue depth equals its
    peak_inflight."""
    res8 = traced_run["res8"]
    manifest, events = telemetry.validate_trace(traced_run["trace"])
    dispatches = [e for e in events if e["type"] == "dispatch"]
    per_dev = {}
    for e in dispatches:
        per_dev[e["device"]] = per_dev.get(e["device"], 0) + 1
    assert sum(per_dev.values()) == res8.nfit
    assert len(per_dev) == res8.devices_used > 1
    assert max(e["queue_depth"] for e in dispatches) == \
        res8.peak_inflight
    counters = events[-1]["counters"]
    assert counters["dispatches"] == res8.nfit
    assert sum(v for k, v in counters.items()
               if k.startswith("dispatches_dev")) == res8.nfit
    assert events[-1]["gauges"]["peak_inflight"] == res8.peak_inflight
    # every fitted TOA got a quality record
    nq = sum(len(e["snr"]) for e in events if e["type"] == "quality")
    assert nq == len(res8.TOA_list)
    # first dispatch per (shape, device) is marked cold
    cold = [(e["shape"], e["device"]) for e in dispatches if e["cold"]]
    assert len(cold) == len(set(cold)) == len(
        {(e["shape"], e["device"]) for e in dispatches})


def test_telemetry_output_byte_identical(traced_run):
    """Telemetry on (8 devices) vs off (1 device) must not perturb the
    campaign output by one byte."""
    with open(traced_run["tim1"], "rb") as f1, \
            open(traced_run["tim8"], "rb") as f8:
        assert f1.read() == f8.read()
    res1, res8 = traced_run["res1"], traced_run["res8"]
    assert len(res1.TOA_list) == len(res8.TOA_list) == 6
    for ta, tb in zip(res1.TOA_list, res8.TOA_list):
        assert (ta.MJD.day, ta.MJD.frac) == (tb.MJD.day, tb.MJD.frac)
        assert ta.flags == tb.flags


def test_pptrace_report_smoke(traced_run, capsys):
    """The report renders every section and its summary dict agrees
    with the executor (what tools/pptrace.py prints)."""
    summary = telemetry.report(traced_run["trace"])
    out = capsys.readouterr().out
    for section in ("pptrace report", "-- devices --", "timeline",
                    "-- queue depth", "-- checkpoint stalls --",
                    "-- cold start", "-- fit quality"):
        assert section in out, section
    res8 = traced_run["res8"]
    assert summary["total_dispatches"] == res8.nfit
    assert sum(summary["device_counts"].values()) == res8.nfit
    assert summary["max_queue_depth"] == res8.peak_inflight
    assert summary["peak_inflight"] == res8.peak_inflight
    assert summary["n_quality"] == len(res8.TOA_list)
    # the module CLI entry drives the same code
    assert telemetry.main(["validate", traced_run["trace"]]) == 0


def test_disabled_mode_emits_nothing(campaign, tmp_path, monkeypatch):
    """Default-off: no tracer object is created, no file is written,
    and the null tracer's enabled flag lets hot paths skip payload
    construction entirely."""
    monkeypatch.setattr(config, "telemetry_path", None)
    tr, owned = telemetry.resolve_tracer(None)
    assert tr is telemetry.NULL_TRACER and not owned
    assert not tr.enabled
    tr.emit("dispatch", anything=1)  # all no-ops
    tr.counter("x")
    tr.gauge_max("y", 3)
    tr.close()
    files, gmodel = campaign
    before = set(os.listdir(tmp_path))
    gt = GetTOAs(files[:1], gmodel, quiet=True)
    gt.get_TOAs(quiet=True, max_iter=25)
    assert set(os.listdir(tmp_path)) == before  # nothing appeared
    # a shared tracer is never closed by the driver that borrowed it
    tr2, owned2 = telemetry.resolve_tracer(
        telemetry.Tracer(str(tmp_path / "t.jsonl"), run="x"))
    assert not owned2
    tr2.close()


def test_gettoas_trace_and_quality_flags(campaign, tmp_path):
    """GetTOAs emits per-archive load/fit events and per-TOA quality
    records from res_arrays; quality_flags=True adds -nfev/-chi2 to
    the .tim lines and stays off by default (golden files
    byte-identical)."""
    from pulseportraiture_tpu.io.tim import toa_string

    files, gmodel = campaign
    trace = str(tmp_path / "gt.jsonl")
    gt = GetTOAs(files[:2], gmodel, quiet=True)
    gt.get_TOAs(quiet=True, max_iter=25, telemetry=trace,
                quality_flags=True)
    manifest, events = telemetry.validate_trace(trace)
    types = [e["type"] for e in events]
    assert types.count("archive_load") == 2
    assert types.count("archive_fit") == 2
    qual = [e for e in events if e["type"] == "quality"]
    assert sum(len(e["snr"]) for e in qual) == len(gt.TOA_list)
    ends = [e for e in events if e["type"] == "run_end"]
    assert ends and ends[-1]["n_toas"] == len(gt.TOA_list)
    for i, toa in enumerate(gt.TOA_list):
        line = toa_string(toa)
        assert " -nfev " in line and " -chi2 " in line, line
        iarch = files[:2].index(toa.archive)
        isub = toa.flags["subint"]
        assert toa.flags["nfev"] == int(gt.nfevals[iarch][isub])
        # chi2 = gof * dof: consistent with the always-present -gof
        assert toa.flags["chi2"] / max(
            gt.red_chi2s[iarch][isub], 1e-300) == pytest.approx(
            round(toa.flags["chi2"] / gt.red_chi2s[iarch][isub]),
            rel=1e-6)  # dof is an integer
    # default off: flag set unchanged
    gt2 = GetTOAs(files[:2], gmodel, quiet=True)
    gt2.get_TOAs(quiet=True, max_iter=25)
    for toa in gt2.TOA_list:
        assert "nfev" not in toa.flags and "chi2" not in toa.flags


def test_stream_quality_flags(campaign):
    """The streaming lane's quality_flags mirrors GetTOAs' (same flag
    names, sourced from the packed results) and defaults off."""
    files, gmodel = campaign
    a = stream_wideband_TOAs(files[:1], gmodel, nsub_batch=2,
                             stream_devices=1, quiet=True,
                             quality_flags=True)
    for toa in a.TOA_list:
        assert isinstance(toa.flags["nfev"], int)
        assert toa.flags["chi2"] > 0.0
    b = stream_wideband_TOAs(files[:1], gmodel, nsub_batch=2,
                             stream_devices=1, quiet=True)
    for toa in b.TOA_list:
        assert "nfev" not in toa.flags and "chi2" not in toa.flags


def test_ipta_campaign_single_trace(campaign, tmp_path):
    """stream_ipta_campaign threads ONE tracer through every
    per-pulsar stream call: campaign + per-pulsar rollups + the
    per-bucket dispatch records all land in one valid trace."""
    from pulseportraiture_tpu.pipeline.ipta import (IPTAJob,
                                                    stream_ipta_campaign)

    files, gmodel = campaign
    trace = str(tmp_path / "ipta.jsonl")
    out = stream_ipta_campaign(
        [IPTAJob("FAKE", files[:2], gmodel),
         IPTAJob("FAKE2", files[2:], gmodel)],
        outdir=str(tmp_path / "tims"), quiet=True, nsub_batch=2,
        telemetry=trace)
    manifest, events = telemetry.validate_trace(trace)
    assert manifest["run"] == "stream_ipta_campaign"
    types = [e["type"] for e in events]
    assert types[0] == "campaign_start"
    assert types.count("pulsar_done") == 2 and "campaign_end" in types
    pds = {e["pulsar"]: e for e in events if e["type"] == "pulsar_done"}
    assert set(pds) == {"FAKE", "FAKE2"}
    assert sum(e["nfit"] for e in pds.values()) == out.nfit
    # dispatch seqs must be UNIQUE across the per-pulsar executors
    # sharing this trace (the report pairs drain events by seq)
    seqs = [e["seq"] for e in events if e["type"] == "dispatch"]
    assert len(seqs) == len(set(seqs)) == out.nfit > 1
    drains = [e["seq"] for e in events if e["type"] == "drain"]
    assert sorted(drains) == sorted(seqs)
    end = [e for e in events if e["type"] == "campaign_end"][0]
    assert end["n_toas"] == len(out.TOA_list)
    telemetry.report(trace, file=open(os.devnull, "w"))  # still renders


def test_env_hooks_and_unknown_ppt_warning(monkeypatch, capsys):
    """PPT_TELEMETRY rides env_overrides ('off' disables explicitly);
    an unrecognized PPT_*-prefixed NAME warns once to stderr with a
    did-you-mean hint — a typo like PPT_STREAM_DEVICE was previously
    silently inert while PPT_STREAM_DEVICES changes behavior."""
    old = config.telemetry_path
    try:
        monkeypatch.setenv("PPT_TELEMETRY", "/tmp/x.jsonl")
        assert "telemetry_path" in config.env_overrides()
        assert config.telemetry_path == "/tmp/x.jsonl"
        monkeypatch.setenv("PPT_TELEMETRY", "off")
        config.env_overrides()
        assert config.telemetry_path is None
        monkeypatch.delenv("PPT_TELEMETRY")

        monkeypatch.setattr(config, "_warned_unknown_ppt", set())
        monkeypatch.setenv("PPT_STREAM_DEVICE", "4")  # the typo
        config.env_overrides()
        err = capsys.readouterr().err
        assert "PPT_STREAM_DEVICE" in err
        assert "PPT_STREAM_DEVICES" in err  # did-you-mean hint
        config.env_overrides()  # warned ONCE per process
        assert capsys.readouterr().err == ""
        # every registered knob passes silently
        monkeypatch.delenv("PPT_STREAM_DEVICE")
        monkeypatch.setenv("PPT_NCHAN", "16")
        config.env_overrides()
        assert capsys.readouterr().err == ""
    finally:
        config.telemetry_path = old


def test_log_levels(capsys):
    """info honors quiet (stdout); warn is never suppressed (stderr);
    unknown levels refuse."""
    telemetry.log("hello", quiet=False)
    telemetry.log("silent", quiet=True)
    telemetry.log("danger", quiet=True, level="warn")
    cap = capsys.readouterr()
    assert "hello" in cap.out and "silent" not in cap.out
    assert "danger" in cap.err
    with pytest.raises(ValueError, match="level"):
        telemetry.log("x", level="debug")


def test_validate_trace_rejects_drift(tmp_path):
    """The schema guard fails loudly on unknown event types, missing
    required fields, bad versions, and headerless files — the drift
    net the bench smoke test throws over the executor."""
    good_manifest = {"type": "manifest", "t": 0.0,
                     "schema": telemetry.TRACE_SCHEMA_VERSION,
                     "run": "x", "t0_unix": 0.0, "backend": "cpu",
                     "devices": [], "config": {}}

    def write(path, records):
        with open(path, "w") as f:
            for r in records:
                f.write(json.dumps(r) + "\n")
        return str(path)

    p = write(tmp_path / "a.jsonl", [good_manifest,
                                     {"type": "warp", "t": 1.0}])
    with pytest.raises(ValueError, match="unknown event type"):
        telemetry.validate_trace(p)
    p = write(tmp_path / "b.jsonl",
              [good_manifest,
               {"type": "dispatch", "t": 1.0, "seq": 0}])
    with pytest.raises(ValueError, match="missing"):
        telemetry.validate_trace(p)
    p = write(tmp_path / "c.jsonl", [dict(good_manifest, schema=99)])
    with pytest.raises(ValueError, match="schema"):
        telemetry.validate_trace(p)
    p = write(tmp_path / "d.jsonl", [{"type": "dispatch", "t": 0.0}])
    with pytest.raises(ValueError, match="manifest"):
        telemetry.validate_trace(p)
    # a trace the drivers actually write passes (tiny hand-rolled one)
    tr = telemetry.Tracer(str(tmp_path / "e.jsonl"), run="unit")
    tr.emit("dispatch", seq=0, device=0, shape="8x64:raw", n=2,
            queue_depth=1, cold=True)
    tr.emit("drain", seq=0, device=0, wait_s=0.1, scatter_s=0.01)
    tr.counter("dispatches")
    tr.gauge_max("peak_inflight", 1)
    tr.close()
    manifest, events = telemetry.validate_trace(
        str(tmp_path / "e.jsonl"))
    assert events[-1]["counters"] == {"dispatches": 1}
    assert events[-1]["gauges"] == {"peak_inflight": 1}
