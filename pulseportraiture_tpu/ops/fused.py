"""Fused DFT -> cross-spectrum hot path (ISSUE 14 / ISSUE 16).

The wideband fit's prepare stage historically ran as separate XLA ops
with full-size intermediates between them: two (nchan, nharm) DFT
pairs for data and model (dr/di/mr/mi), then the elementwise
cross-spectrum, then the per-channel power reductions — six
(nchan, nharm) HBM-resident arrays to produce the two the Newton loop
actually reads (Xr, Xi).  On an MXU that is the difference between a
roofline matmul and a pipeline of HBM round-trips (BENCH_r04/r05: the
fit lane flat at 22.1-22.4k TOAs/s, mfu 0.121, since round 4).

`fused_cross_spectrum` blocks the channel axis through ONE lax.scan:
each step DFTs a channel block (reusing ops.fourier.rfft_mm — the
matmul-DFT single source of truth, so precision/fold semantics are
shared), forms the block's weighted cross-spectrum and model power in
registers/VMEM-sized tiles, and emits only the persistent outputs.
Per-row matmul results and per-row reductions are BITWISE identical to
the unblocked program (blocking never re-associates a row's
contraction; guarded by tests/test_fastpath.py and the .tim byte gates
in tests/test_stream.py), which is what lets config.fit_fused flip
with zero behavior drift.

R17 measured the scan CPU-honest 0.84x: XLA will not fuse a dot into
its consumers, so even the hand-blocked program round-trips its block
intermediates.  `fused_cross_spectrum_pallas` (ISSUE 16) is the real
fusion: ONE Pallas kernel per channel tile runs the DFT matmuls, the
weighted cross-spectrum, and the model-power reduction with every
intermediate VMEM-resident — no HBM traffic between the stages.  It
shares the scan's zero-padded channel tiling and the rfft_mm twiddle
construction (ops.fourier._rfft_weights / _rfft_fold_weights — the
single source of truth), so each tile's gemm is shape-identical to a
scan block's and the outputs are BITWISE equal to the scan (and hence
to the unfused program).  Developed and gated entirely on CPU via
``pallas_call(interpret=True)``; the compiled-kernel tuning sweep is
pre-scoped for the chip session (benchmarks/BENCHMARKS.md config 6/2).

`fused_decode_cross_spectrum_pallas` extends the same treatment down
the raw streaming lane for sub-byte packed payloads: one kernel per
channel tile chains bit-plane unpack -> affine decode -> min-window
baseline -> DFT -> cross-spectrum (+ the exact time-domain Parseval
rows the windowed fit's full-spectrum Sd needs), so the decoded
portrait never materializes in HBM between the decode and the fit's
prepare — multiplying the R18 wire-byte win by an HBM-traffic win.

Scope: both fused programs are the WINDOWED hot path — the caller's
full-spectrum data power must come from the exact time-domain Parseval
form (fit/portrait._parseval_Sd, whose per-channel pieces the decode
kernel emits); fit/portrait only activates fusion when nharm_eff is
set.
"""

import jax
import jax.numpy as jnp
import numpy as np

from .. import config

__all__ = ["fused_cross_spectrum", "fused_cross_spectrum_pallas",
           "fused_decode_cross_spectrum_pallas", "use_fit_pallas",
           "fused_block_default", "HAVE_PALLAS_FUSED"]

try:  # pallas imports cleanly on CPU (lowering is backend-specific,
    # importing is not); guarded anyway so a runtime built without the
    # experimental package degrades to the scan instead of breaking
    # module import
    from jax.experimental import pallas as pl
    _PALLAS_IMPORT_ERROR = None
except Exception as _e:  # pragma: no cover - environment-specific
    pl = None
    _PALLAS_IMPORT_ERROR = _e

# True when the Pallas kernels below are importable; config.fit_pallas
# ('auto') dispatches to them on TPU backends, and forcing the knob on
# elsewhere runs them under pallas_call(interpret=True) — the CPU
# development/gating mode (ISSUE 16).
HAVE_PALLAS_FUSED = pl is not None

# Channel-block target: big enough that the block DFT matmul amortizes
# loop overhead, small enough that a block's (cb, nbin) input tile and
# (cb, nharm) output tiles stay cache/VMEM-resident at production
# shapes (512ch x 2048bin f32: 32 x 2048 x 4B = 256 KB in, 4 x 32 x
# nharm out).
_BLOCK_TARGET = 32


def fused_block_default():
    """The channel-block target: config.fused_block / PPT_FUSED_BLOCK
    when set (the chip-session lattice sweep's no-code-edit override),
    else the built-in target.  Read at trace time; the batch wrappers
    carry the resolved value in their program-cache keys
    (fit/portrait.resolve_fit_fused) so a mid-process override
    retraces."""
    b = getattr(config, "fused_block", None)
    if b is None:
        return _BLOCK_TARGET
    b = int(b)
    if b < 1:
        raise ValueError(
            f"config.fused_block must be a positive int or None; "
            f"got {b!r}")
    return b


def _block_size(nchan, target=None):
    """Block size for the channel tiling: the target (explicit >
    config.fused_block > built-in), clamped to nchan.  A ragged
    channel count is ZERO-PADDED up to a block multiple rather than
    degrading the block (a degenerate 1-row block would lower the DFT
    matmul to a gemv, whose contraction order differs from the gemm
    rows the unfused program computes — measured non-bitwise on CPU;
    zero pad rows cost their flops but keep every real row's kernel
    identical)."""
    if target is None:
        target = fused_block_default()
    return min(int(target), int(nchan))


def use_fit_pallas(setting=None):
    """Whether the fused prepare stage should run the Pallas kernel
    instead of the hand-blocked scan: config.fit_pallas (strict
    tri-state like fit_fused).

      False:  never (the scan — bit-stable across releases).
      'auto': the compiled kernel on TPU backends when available;
              the scan elsewhere (CPU never silently pays interpret
              overhead).
      True:   force the kernel everywhere — on non-TPU backends it
              runs under pallas_call(interpret=True), the CPU
              development/gating mode.  Loud RuntimeError when Pallas
              is unavailable: a forced A/B arm must not silently
              measure the scan.

    Only meaningful when the fused lane itself is active (fit_fused +
    harmonic window); fit/portrait.resolve_fit_fused normalizes the
    dead combinations so the knob never keys a redundant program."""
    if setting is None:
        setting = getattr(config, "fit_pallas", "auto")
    if setting is False:
        return False
    if setting is True:
        if not HAVE_PALLAS_FUSED:
            raise RuntimeError(
                "config.fit_pallas=True but jax.experimental.pallas "
                f"failed to import: {_PALLAS_IMPORT_ERROR!r}")
        return True
    from ..tune.capability import resolve_auto

    return HAVE_PALLAS_FUSED and resolve_auto("fit_pallas", setting)


def fused_cross_spectrum(port, model, w, nharm, precision=None,
                         fold=None, want_m2=False, block=None,
                         pallas=None):
    """One blocked pass: windowed split-real DFT of data + model ->
    weighted cross-spectrum (+ model power), never materializing the
    full (nchan, nharm) DFT intermediates.

    port/model: (nchan, nbin) time-domain portraits (model may be the
    shared template — under vmap with in_axes=None its per-block DFT
    stays unbatched and hoists).  w: (nchan, nharm) weights already
    sliced to the harmonic window.  nharm: the window (static).
    want_m2=False returns (Xr, Xi, S0) with S0 the per-channel model
    power (the no-scattering lane); want_m2=True returns (Xr, Xi, M2w)
    with the full weighted model power spectrum (the scattering lane,
    which needs it per harmonic).

    pallas: route through the Pallas kernel variant (None = resolve
    config.fit_pallas at trace time).  block: channel-block override —
    threaded through BOTH implementations (the Pallas dispatch used to
    silently drop it; a tuning sweep must measure what it sets).

    Every output row is bitwise identical to the unfused program's —
    the per-row DFT contraction and the per-row harmonic reduction are
    untouched by channel blocking, in the scan and in the kernel."""
    if pallas is None:
        pallas = use_fit_pallas()
    if pallas:
        return fused_cross_spectrum_pallas(port, model, w, nharm,
                                           precision=precision,
                                           fold=fold, want_m2=want_m2,
                                           block=block)
    from .fourier import rfft_mm

    nchan, nbin = port.shape[-2], port.shape[-1]
    cb = _block_size(nchan, block)
    nblk = -(-nchan // cb)
    pad = nblk * cb - nchan

    def tile(x, width):
        if pad:
            x = jnp.concatenate(
                [x, jnp.zeros((pad, width), x.dtype)], axis=0)
        return x.reshape(nblk, cb, width)

    pb = tile(port, nbin)
    mb = tile(model, nbin)
    wb = tile(w, nharm)

    def step(carry, xs):
        p, m, wk = xs
        drb, dib = rfft_mm(p, precision=precision, nharm=nharm,
                           fold=fold)
        mrb, mib = rfft_mm(m, precision=precision, nharm=nharm,
                           fold=fold)
        Xrb = (drb * mrb + dib * mib) * wk
        Xib = (dib * mrb - drb * mib) * wk
        m2b = (mrb**2 + mib**2) * wk
        out2 = m2b if want_m2 else jnp.sum(m2b, axis=-1)
        return carry, (Xrb, Xib, out2)

    _, (Xr, Xi, o2) = jax.lax.scan(step, 0, (pb, mb, wb))
    Xr = Xr.reshape(nblk * cb, nharm)[:nchan]
    Xi = Xi.reshape(nblk * cb, nharm)[:nchan]
    o2 = (o2.reshape(nblk * cb, nharm)[:nchan] if want_m2
          else o2.reshape(nblk * cb)[:nchan])
    return Xr, Xi, o2


def _require_pallas():
    if pl is None:  # pragma: no cover - environment-specific
        raise RuntimeError(
            "the Pallas fused kernels need jax.experimental.pallas, "
            f"which failed to import: {_PALLAS_IMPORT_ERROR!r}")


def _resolve_kernel_opts(nbin, precision, fold, interpret):
    """Shared knob resolution for both kernels: matmul precision and
    the fold-symmetry path follow rfft_mm exactly (single source of
    truth for the semantics), interpret defaults to every non-TPU
    backend — the compiled kernel is a TPU artifact, everything else
    runs the reference interpreter."""
    from .fourier import _default_precision, use_dft_fold

    if precision is None:
        precision = _default_precision()
    if fold is None:
        fold = use_dft_fold()
    fold = bool(fold) and nbin % 2 == 0 and nbin >= 8
    if interpret is None:
        from ..tune.capability import resolve_auto

        interpret = resolve_auto("pallas_interpret", "auto")
    return precision, fold, bool(interpret)


def _twiddles(nbin, nharm, dtype_str, fold):
    """The DFT weight matrices as kernel inputs, from the SAME cached
    host constructors rfft_mm uses (ops.fourier._rfft_weights /
    _rfft_fold_weights) — twiddle construction has exactly one
    implementation in this codebase."""
    from .fourier import _rfft_fold_weights, _rfft_weights

    if fold:
        Wc_h, Ws_h, sgn = _rfft_fold_weights(nbin, dtype_str, nharm)
        return (jnp.asarray(Wc_h), jnp.asarray(Ws_h),
                jnp.asarray(sgn).reshape(1, -1))
    Wc, Ws = _rfft_weights(nbin, dtype_str, nharm)
    return (jnp.asarray(Wc), jnp.asarray(Ws))


def _dft_tile(x, tw, fold, precision):
    """Split-real DFT of one (cb, nbin) tile against pre-loaded
    twiddle refs — the in-kernel mirror of rfft_mm's two arms, same
    matmul shapes and op order so every row is bitwise identical to
    the scan's rfft_mm call on the same block."""
    if fold:
        Wc_h, Ws_h, sgn = tw
        n = x.shape[-1]
        head = x[..., 1:n // 2]
        tail = jnp.flip(x[..., n // 2 + 1:], axis=-1)
        dr = (jnp.matmul(head + tail, Wc_h, precision=precision)
              + x[..., 0:1] + x[..., n // 2:n // 2 + 1] * sgn)
        di = jnp.matmul(head - tail, Ws_h, precision=precision)
        return dr, di
    Wc, Ws = tw
    return (jnp.matmul(x, Wc, precision=precision),
            jnp.matmul(x, Ws, precision=precision))


def _full_spec(t):
    """BlockSpec for a broadcast (non-tiled) kernel input: every grid
    step maps the whole array."""
    return pl.BlockSpec(t.shape, lambda i: (0,) * t.ndim)


def _row_spec(cb, width):
    """BlockSpec for a channel-tiled (nchan, width) operand."""
    return pl.BlockSpec((cb, width), lambda i: (i, 0))


def fused_cross_spectrum_pallas(port, model, w, nharm, precision=None,
                                fold=None, want_m2=False, block=None,
                                interpret=None):
    """Pallas kernel variant of :func:`fused_cross_spectrum` — ONE
    VMEM-resident kernel per channel tile computing the two DFT
    matmuls, the weighted cross-spectrum, and the model-power
    reduction without touching HBM between the stages, the fusion the
    hand-blocked XLA program cannot express (XLA will not fuse a dot
    into its consumers).

    interpret: None = compiled on TPU, interpreter elsewhere (the CPU
    development/gating mode, tests/test_pallas_interpret.py).  Tiling,
    zero-padding, and twiddles are shared with the scan, so outputs
    are BITWISE identical to it at any block size."""
    _require_pallas()
    nchan, nbin = port.shape[-2], port.shape[-1]
    dt = port.dtype
    precision, fold, interpret = _resolve_kernel_opts(
        nbin, precision, fold, interpret)
    cb = _block_size(nchan, block)
    nblk = -(-nchan // cb)
    pad = nblk * cb - nchan

    def padded(x, width):
        if pad:
            x = jnp.concatenate(
                [x, jnp.zeros((pad, width), x.dtype)], axis=0)
        return x

    tw = _twiddles(nbin, nharm, str(dt), fold)
    ntw = len(tw)

    def kernel(p_ref, m_ref, w_ref, *rest):
        tw_t = tuple(r[...] for r in rest[:ntw])
        xr_ref, xi_ref, o2_ref = rest[ntw:]
        wk = w_ref[...]
        dr, di = _dft_tile(p_ref[...], tw_t, fold, precision)
        mr, mi = _dft_tile(m_ref[...], tw_t, fold, precision)
        xr_ref[...] = (dr * mr + di * mi) * wk
        xi_ref[...] = (di * mr - dr * mi) * wk
        m2 = (mr**2 + mi**2) * wk
        if want_m2:
            o2_ref[...] = m2
        else:
            # per-row harmonic reduction inside the tile; (cb, 1)
            # keeps the output 2-D (TPU-friendly), squeezed below
            o2_ref[...] = jnp.sum(m2, axis=-1, keepdims=True)

    o2_w = nharm if want_m2 else 1
    Xr, Xi, o2 = pl.pallas_call(
        kernel,
        grid=(nblk,),
        in_specs=[_row_spec(cb, nbin), _row_spec(cb, nbin),
                  _row_spec(cb, nharm)] + [_full_spec(t) for t in tw],
        out_specs=[_row_spec(cb, nharm), _row_spec(cb, nharm),
                   _row_spec(cb, o2_w)],
        out_shape=[jax.ShapeDtypeStruct((nblk * cb, nharm), dt),
                   jax.ShapeDtypeStruct((nblk * cb, nharm), dt),
                   jax.ShapeDtypeStruct((nblk * cb, o2_w), dt)],
        interpret=interpret,
    )(padded(port, nbin), padded(model, nbin), padded(w, nharm), *tw)
    Xr = Xr[:nchan]
    Xi = Xi[:nchan]
    o2 = o2[:nchan] if want_m2 else o2[:nchan, 0]
    return Xr, Xi, o2


def fused_decode_cross_spectrum_pallas(raw, scl, offs, model, w, nharm,
                                       *, code, nbin, precision=None,
                                       fold=None, block=None,
                                       interpret=None):
    """Raw-lane decode+DFT tile (ISSUE 16 tentpole, layer 2): ONE
    Pallas kernel per channel tile chains bit-plane unpack -> affine
    sample decode -> min-window baseline -> DFT matmuls -> weighted
    cross-spectrum, so the decoded portrait never materializes in HBM
    between the decode stage and the fit's prepare.

    raw: (nchan, bpc) uint8 — the packed payload RESHAPED so each
    channel's bytes form a row (valid when nbin*nbit % 8 == 0; the
    stream front normalizes the knob off otherwise).  scl/offs:
    (nchan,) DAT_SCL/DAT_OFFS.  model: (nchan, nbin) in the compute
    dtype.  w: (nchan, nharm) weights sliced to the harmonic window.
    code: 'p1' | 'p2' | 'p4'.

    Returns (Xr, Xi, S0, pwr, x0): the windowed cross-spectrum triple
    plus the per-channel time-domain Parseval pieces — ``pwr`` the
    mean-removed power (even-nbin Nyquist term included) and ``x0``
    the channel sum — computed on the in-kernel decoded tile with
    exactly fit/portrait._parseval_Sd's per-channel ops, so the
    caller's Sd assembly is bitwise identical to the decoded lane's.

    The decode chain calls the SAME ops the materialized lane uses
    (ops.decode.unpack_bitplanes / affine_decode,
    ops.noise.min_window_baseline) on per-channel tiles; every op is
    per-channel along the last axis, so tiling changes nothing and the
    decoded values are bit-exact against ops.decode.decode_stokes_I —
    which is what makes the .tim output byte-identical to the
    decoded-fallback oracle."""
    _require_pallas()
    from .decode import PACKED_BITS, affine_decode
    from .noise import min_window_baseline

    nbit = PACKED_BITS.get(code)
    if nbit is None:
        raise ValueError(
            f"fused_decode_cross_spectrum_pallas: packed sub-byte "
            f"codes only (got {code!r})")
    if (nbin * nbit) % 8 != 0:
        raise ValueError(
            f"fused_decode_cross_spectrum_pallas: nbin*nbit must be "
            f"byte-aligned per channel (nbin={nbin}, nbit={nbit})")
    bpc = (nbin * nbit) // 8
    nchan = raw.shape[-2]
    dt = w.dtype
    precision, fold, interpret = _resolve_kernel_opts(
        nbin, precision, fold, interpret)
    cb = _block_size(nchan, block)
    nblk = -(-nchan // cb)
    pad = nblk * cb - nchan

    def padded(x, width):
        if pad:
            x = jnp.concatenate(
                [x, jnp.zeros((pad, width), x.dtype)], axis=0)
        return x

    tw = _twiddles(nbin, nharm, str(dt), fold)
    ntw = len(tw)
    even = nbin % 2 == 0
    # the Parseval Nyquist sign row, exactly _parseval_Sd's construction
    sgn_p = (jnp.asarray((-1.0) ** np.arange(nbin), dt).reshape(1, nbin)
             if even else None)
    extra = (sgn_p,) if even else ()

    def kernel(raw_ref, scl_ref, offs_ref, m_ref, w_ref, *rest):
        tw_t = tuple(r[...] for r in rest[:ntw])
        rest = rest[ntw:]
        if even:
            sgn_t = rest[0][...]
            rest = rest[1:]
        xr_ref, xi_ref, s0_ref, pwr_ref, x0_ref = rest
        # --- decode: the same ops as the materialized lane, on a tile
        from .decode import unpack_bitplanes

        samples = unpack_bitplanes(raw_ref[...], nbit, nbin)
        x = affine_decode(samples, scl_ref[...][:, 0],
                          offs_ref[...][:, 0], dt, code=code)
        x = x - min_window_baseline(x)[..., None]
        # --- Parseval rows (fit/portrait._parseval_Sd per-channel ops)
        x0 = jnp.sum(x, axis=-1, keepdims=True)
        mu = x0 / nbin
        pwr = nbin * jnp.sum((x - mu) ** 2, axis=-1, keepdims=True)
        if even:
            xn = jnp.sum(x * sgn_t, axis=-1, keepdims=True)
            pwr = pwr + xn**2
        x0_ref[...] = x0
        pwr_ref[...] = pwr
        # --- DFT + cross-spectrum, identical to the portrait kernel
        wk = w_ref[...]
        dr, di = _dft_tile(x, tw_t, fold, precision)
        mr, mi = _dft_tile(m_ref[...], tw_t, fold, precision)
        xr_ref[...] = (dr * mr + di * mi) * wk
        xi_ref[...] = (di * mr - dr * mi) * wk
        s0_ref[...] = jnp.sum((mr**2 + mi**2) * wk, axis=-1,
                              keepdims=True)

    Xr, Xi, S0, pwr, x0 = pl.pallas_call(
        kernel,
        grid=(nblk,),
        in_specs=[_row_spec(cb, bpc), _row_spec(cb, 1),
                  _row_spec(cb, 1), _row_spec(cb, nbin),
                  _row_spec(cb, nharm)]
        + [_full_spec(t) for t in tw + extra],
        out_specs=[_row_spec(cb, nharm), _row_spec(cb, nharm),
                   _row_spec(cb, 1), _row_spec(cb, 1),
                   _row_spec(cb, 1)],
        out_shape=[jax.ShapeDtypeStruct((nblk * cb, nharm), dt),
                   jax.ShapeDtypeStruct((nblk * cb, nharm), dt),
                   jax.ShapeDtypeStruct((nblk * cb, 1), dt),
                   jax.ShapeDtypeStruct((nblk * cb, 1), dt),
                   jax.ShapeDtypeStruct((nblk * cb, 1), dt)],
        interpret=interpret,
    )(padded(raw.reshape(nchan, bpc), bpc),
      padded(scl.reshape(nchan, 1).astype(dt), 1),
      padded(offs.reshape(nchan, 1).astype(dt), 1),
      padded(model.astype(dt), nbin), padded(w, nharm),
      *(tw + extra))
    return (Xr[:nchan], Xi[:nchan], S0[:nchan, 0], pwr[:nchan, 0],
            x0[:nchan, 0])
