"""Option-lattice coverage for the fast fit engine (ISSUE 1 satellite).

The engine's knob lattice — fit flags x bounds x harmonic window x
cross-spectrum dtype x compensated reductions x instrumental response —
was previously tested only at directed points; a knob interaction that
broke an untested combination (e.g. bounds under a windowed bf16
scattering fit) would ship silently.  This sweeps the full lattice on a
tiny synthetic batch with KNOWN injected (phi, DM, tau), asserting
convergence (return codes in the engine's success vocabulary), truth
recovery within per-combo tolerances, and — for the no-scatter,
no-response combos — agreement with the independent NumPy reference.

A directed fast subset runs in tier-1; the full lattice (every
combination, ~60 compiled programs) is marked `slow`.
"""

import itertools

import jax.numpy as jnp
import numpy as np
import pytest

from pulseportraiture_tpu import config
from pulseportraiture_tpu.config import Dconst
from pulseportraiture_tpu.fit import FitFlags
from pulseportraiture_tpu.fit.portrait import (fit_portrait_batch_fast,
                                               model_harmonic_window)
from pulseportraiture_tpu.fit.reference_numpy import fit_portrait_numpy

NB, NCHAN, NBIN = 2, 8, 512
P, NU_FIT = 0.003, 1500.0
PHI_TRUE = np.array([0.021, -0.0137])
DM_TRUE = np.array([0.4, -0.3])  # small DM offsets [pc cm^-3]
TAU_TRUE = 0.02  # rotations at NU_FIT (scatter combos)
ALPHA_TRUE = -4.0
NOISE = 0.003

FLAG_SETS = {
    "phiDM": (FitFlags(True, True, False, False, False), False),
    "scat": (FitFlags(True, True, False, True, True), True),
}
BOUNDS = {
    # generous box containing truth; exercises the projected-gradient
    # path and the TNC-vocabulary return codes
    "on": np.array([[-0.5, 0.5], [-50.0, 50.0], [-1.0, 1.0],
                    [-8.0, 1.0], [-8.0, 0.0]]),
    "off": None,
}


def _synth(with_ir=False, scattered=False):
    """Tiny batch with injected truth, built in f64 numpy (independent
    of the engine's DFT path)."""
    from pulseportraiture_tpu.models.gaussian import gen_gaussian_portrait
    from pulseportraiture_tpu.synth import default_test_model

    rng = np.random.default_rng(7)
    tm = default_test_model(NU_FIT)
    freqs = np.linspace(1300.0, 1899.0, NCHAN)
    params = {k: np.asarray(v, np.float64)
              for k, v in tm.params_pytree().items()}
    model = np.asarray(gen_gaussian_portrait(
        {k: jnp.asarray(v) for k, v in params.items()}, jnp.asarray(freqs),
        tm.nu_ref, NBIN, P=P, code=tm.code, scattered=False), np.float64)
    nharm = NBIN // 2 + 1
    k = np.arange(nharm)
    mFT = np.fft.rfft(model, axis=-1)
    ir = None
    if with_ir:
        # mild per-channel low-pass response with a linear phase ramp
        sig = 80.0 + 10.0 * np.arange(NCHAN)[:, None]
        ir = (np.exp(-0.5 * (k[None, :] / sig) ** 2)
              * np.exp(-2j * np.pi * k[None, :] * 0.001))
    ports = np.empty((NB, NCHAN, NBIN))
    for i in range(NB):
        t_n = PHI_TRUE[i] + (Dconst * DM_TRUE[i] / P) * (
            freqs**-2.0 - NU_FIT**-2.0)
        # rotate by -t_n: the engine's objective phasor is e^{+2pi i k t}
        # (C peaks where the rotation is undone), matching bench.py
        dFT = mFT * np.exp(-2j * np.pi * np.outer(t_n, k))
        if scattered:
            taus = TAU_TRUE * (freqs / NU_FIT) ** ALPHA_TRUE
            B = 1.0 / (1.0 + 2j * np.pi * taus[:, None] * k[None, :])
            dFT = dFT * B
        if ir is not None:
            dFT = dFT * ir
        ports[i] = np.fft.irfft(dFT, n=NBIN, axis=-1)
    ports += NOISE * rng.standard_normal(ports.shape)
    return (ports.astype(np.float32), model.astype(np.float32),
            freqs.astype(np.float32), ir)


def _run_combo(flag_key, bounds_key, window, xspec, comp, ir_key):
    flags, scattered = FLAG_SETS[flag_key]
    with_ir = ir_key == "ir"
    ports, model, freqs, ir = _synth(with_ir=with_ir,
                                     scattered=scattered)
    old_x, old_c = config.cross_spectrum_dtype, config.scatter_compensated
    config.cross_spectrum_dtype = ("bfloat16" if xspec == "bf16"
                                   else None)
    config.scatter_compensated = comp == "comp"
    try:
        hwin = (model_harmonic_window(model, NBIN)
                if window == "derived" else False)
        th0 = np.zeros((NB, 5), np.float32)
        if scattered:
            th0[:, 3] = np.log10(TAU_TRUE)
            th0[:, 4] = ALPHA_TRUE
        r = fit_portrait_batch_fast(
            jnp.asarray(ports), model, jnp.full((NB, NCHAN), NOISE,
                                                jnp.float32),
            jnp.asarray(freqs), P, NU_FIT, theta0=jnp.asarray(th0),
            fit_flags=flags, log10_tau=scattered, max_iter=40,
            ir_FT=ir, harmonic_window=hwin if hwin else False,
            bounds=BOUNDS[bounds_key])
    finally:
        config.cross_spectrum_dtype = old_x
        config.scatter_compensated = old_c
    return r, ports, model, freqs


def _check_combo(flag_key, bounds_key, window, xspec, comp, ir_key):
    flags, scattered = FLAG_SETS[flag_key]
    r, ports, model, freqs = _run_combo(flag_key, bounds_key, window,
                                        xspec, comp, ir_key)
    rc = np.asarray(r.return_code)
    # success vocabulary: 0/2 historical, 1 = interior convergence in
    # bounds mode (config.RCSTRINGS)
    assert np.all(np.isin(rc, [0, 1, 2])), rc
    assert np.all(np.isfinite(np.asarray(r.phi)))

    # truth recovery at nu_fit reference (re-reference the reported phi
    # from nu_DM back to NU_FIT through the fitted DM)
    phi = np.asarray(r.phi) + (Dconst * np.asarray(r.DM) / P) * (
        np.float64(NU_FIT) ** -2.0 - np.asarray(r.nu_DM) ** -2.0)
    phi = (phi + 0.5) % 1.0 - 0.5
    # per-combo tolerance: bf16 X quantization doesn't average down at
    # 8 channels the way it does at 512, so those combos get more room
    tol_phi = 5e-4 if xspec == "bf16" else 2e-4
    assert np.all(np.abs(phi - PHI_TRUE) < tol_phi), (
        phi - PHI_TRUE, tol_phi)
    assert np.all(np.abs(np.asarray(r.DM) - DM_TRUE) < 0.3), r.DM
    if scattered:
        tau = np.asarray(r.tau) * (NU_FIT / np.asarray(r.nu_tau)) ** \
            np.asarray(r.alpha)
        rel = np.abs(tau - TAU_TRUE) / TAU_TRUE
        tol_tau = 0.05 if xspec == "bf16" else 0.02
        assert np.all(rel < tol_tau), (rel, tol_tau)

    # independent NumPy oracle where it applies
    if not scattered and ir_key == "noir":
        ref = fit_portrait_numpy(
            np.asarray(ports[0], np.float64),
            np.asarray(model, np.float64),
            np.full(NCHAN, NOISE), np.asarray(freqs, np.float64),
            P, NU_FIT)
        phi_ref = (ref["phi"] + 0.5) % 1.0 - 0.5
        assert abs(phi[0] - phi_ref) < tol_phi


# --- directed fast subset (tier-1) --------------------------------------

FAST_COMBOS = [
    ("phiDM", "off", "full", "bf16", "plain", "noir"),
    ("phiDM", "on", "derived", "f32", "plain", "noir"),
    ("scat", "off", "derived", "bf16", "plain", "noir"),
    ("scat", "on", "full", "f32", "comp", "noir"),
    ("scat", "off", "full", "f32", "plain", "ir"),
]


# the compensated-scattering combo is the heaviest directed case
# (~25 s); it rides the @slow full lattice, and tier-1 keeps the comp
# lane via the scatter-compensated fits in tests/test_fit.py
@pytest.mark.parametrize(
    "combo",
    [pytest.param(c, id="-".join(c),
                  marks=([pytest.mark.slow] if c[4] == "comp" else []))
     for c in FAST_COMBOS])
def test_option_lattice_directed(combo):
    _check_combo(*combo)


# --- full lattice (slow) ------------------------------------------------

ALL_COMBOS = [
    (fk, bk, win, xs, cp, ir)
    for fk, bk, win, xs, cp, ir in itertools.product(
        FLAG_SETS, BOUNDS, ("full", "derived"), ("bf16", "f32"),
        ("plain", "comp"), ("noir", "ir"))
    # compensated is a scattering-engine knob; on the no-scatter path
    # it is dead by construction (stream._raw_fit_fn normalizes it
    # away), so those combos are not distinct programs
    if not (cp == "comp" and fk == "phiDM")
]


@pytest.mark.slow
@pytest.mark.parametrize("combo", ALL_COMBOS,
                         ids=["-".join(c) for c in ALL_COMBOS])
def test_option_lattice_full(combo):
    _check_combo(*combo)
