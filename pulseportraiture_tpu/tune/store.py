"""Persisted tuning database (ISSUE 19 tentpole, layer 2b).

One JSON file holds the autotuner's accepted winners, keyed by
(backend fingerprint, shape class).  The contract the autotuner and
the campaign CLIs rely on:

- **Zero re-sweeps on a warm DB**: a second run with the same
  fingerprint + shape class loads the stored knobs and never times a
  candidate (witnessed in the trace by ``tune_apply`` with
  ``db_hit=true`` and no ``tune_sweep`` events — bench_autotune.py
  gates it).
- **Stale or corrupt DBs are refused LOUDLY, never fatally**: a file
  that fails to parse, has the wrong schema version, or was measured
  under a different backend fingerprint produces a ``warnings.warn``
  and an empty store — a campaign falls back to defaults, it never
  crashes on somebody's leftover DB.
- **Atomic writes**: tmp + ``os.replace`` so a crashed sweep can't
  leave a half-written DB for the next run to choke on.

Schema (version 1)::

    {"version": 1,
     "fingerprint": "cpu:TFRT_CPU_0:jax-0.4...",
     "entries": {"<shape_class>": {"knobs": {...},
                                   "default_s": ..., "tuned_s": ...,
                                   "n_swept": ...}}}

One file == one fingerprint: heterogeneous fleets point each host at
its own path (or share a directory — see tune.db_path_for).
"""

import json
import os
import warnings

from .capability import backend_fingerprint

__all__ = ["TuningStore", "SCHEMA_VERSION"]

SCHEMA_VERSION = 1


class TuningStore:
    """Load/store tuning winners at ``path`` for the live backend
    fingerprint (override with ``fingerprint=`` for tests)."""

    def __init__(self, path, fingerprint=None):
        self.path = str(path)
        self.fingerprint = (fingerprint if fingerprint is not None
                            else backend_fingerprint())

    # ------------------------------------------------------------------

    def _load_raw(self):
        """The validated entries dict, or {} with a loud warning when
        the file is missing-but-unreadable, corrupt, mis-versioned, or
        fingerprint-stale."""
        if not os.path.exists(self.path):
            return {}
        try:
            with open(self.path) as fh:
                doc = json.load(fh)
        except (OSError, ValueError) as e:
            warnings.warn(
                f"tuning DB {self.path!r} is unreadable/corrupt "
                f"({type(e).__name__}: {e}); ignoring it and running "
                "with default knobs (delete the file to silence this)",
                stacklevel=3)
            return {}
        if not isinstance(doc, dict) \
                or doc.get("version") != SCHEMA_VERSION \
                or not isinstance(doc.get("entries"), dict):
            warnings.warn(
                f"tuning DB {self.path!r} has an unknown schema "
                f"(version={doc.get('version') if isinstance(doc, dict) else None!r}); "
                "ignoring it and running with default knobs",
                stacklevel=3)
            return {}
        if doc.get("fingerprint") != self.fingerprint:
            warnings.warn(
                f"tuning DB {self.path!r} was measured on backend "
                f"{doc.get('fingerprint')!r} but this process is "
                f"{self.fingerprint!r}; ignoring it and running with "
                "default knobs (re-run the autotune sweep here)",
                stacklevel=3)
            return {}
        return doc["entries"]

    def get(self, shape_class):
        """The stored entry dict for ``shape_class`` (``{"knobs":
        ..., ...}``) or None."""
        ent = self._load_raw().get(str(shape_class))
        if ent is not None and not isinstance(ent.get("knobs"), dict):
            warnings.warn(
                f"tuning DB {self.path!r} entry {shape_class!r} is "
                "malformed; ignoring it", stacklevel=2)
            return None
        return ent

    def put(self, shape_class, knobs, **meta):
        """Persist one sweep's winners (atomic; merges with existing
        same-fingerprint entries — a stale-fingerprint file is
        OVERWRITTEN, matching the loud refusal on load)."""
        entries = self._load_raw()
        entries[str(shape_class)] = {"knobs": dict(knobs), **meta}
        doc = {"version": SCHEMA_VERSION,
               "fingerprint": self.fingerprint,
               "entries": entries}
        d = os.path.dirname(os.path.abspath(self.path))
        os.makedirs(d, exist_ok=True)
        tmp = self.path + ".tmp"
        with open(tmp, "w") as fh:
            json.dump(doc, fh, indent=1, sort_keys=True)
        os.replace(tmp, self.path)

    def shape_classes(self):
        return sorted(self._load_raw())
