"""pulseportraiture_tpu — a TPU-native wideband pulsar-timing framework.

A from-scratch JAX/XLA implementation of the capabilities of
PulsePortraiture (Pennucci, Demorest & Ransom 2014; Pennucci 2019):
measuring wideband pulse times-of-arrival (TOAs), dispersion measures
(DMs), and scattering parameters from folded radio-pulsar archives, and
building frequency-dependent template portraits from data.

Design stance (see SURVEY.md §7): one autodiff objective instead of
hand-derived gradients; batched `vmap`/`shard_map` fits instead of
Python loops; jittable fixed-shape optimizers instead of scipy; masks
instead of ragged fancy-indexing; float64 on host for TOA arithmetic,
float32 on TPU for the chi^2 surface.

Subpackages
-----------
ops       - Fourier-domain numerical kernels (rotation, scattering, noise)
fit       - fit engines (1-D FFTFIT, 2-D..5-param portrait fit, LM)
models    - template portrait models (gaussian, spline/PCA, wavelet)
io        - PSRFITS / model-file / TOA-file I/O (no PSRCHIVE dependency)
pipeline  - high-level pipelines (toas, align, spline, gauss, zap)
serve     - continuous-batching TOA service (warm executor, ppserve)
parallel  - device-mesh sharding helpers
telemetry - campaign event tracing, run manifests, pptrace report
synth     - synthetic data generation (the test fixture)
viz       - matplotlib visualization (host-side)
utils     - MJD arithmetic, misc
"""

import jax

# TOA arithmetic needs float64 on host; TPU hot paths cast to f32
# explicitly (see fit/portrait.py).
jax.config.update("jax_enable_x64", True)

from .config import *  # noqa: F401,F403

__version__ = "0.1.0"
