"""Single-core NumPy/SciPy reference implementation of the wideband
portrait fit.

This is the accuracy oracle and the performance baseline demanded by
BASELINE.md: a deliberately straightforward, independent implementation
(numpy rFFTs + scipy trust-ncg with finite-difference-free analytic
gradient via complex arithmetic) that the JAX engine must match to
|dphi| < 1e-4 and beat by >=50x in throughput.  Kept free of any JAX
imports on purpose.
"""

import numpy as np
import scipy.optimize as opt

from ..config import Dconst, F0_fact


def _objective_pieces(theta, dFT, mFT, w, freqs, P, nu_fit):
    phi, DM = theta
    nharm = dFT.shape[-1]
    k = np.arange(nharm)
    t_n = phi + (Dconst * DM / P) * (freqs**-2.0 - nu_fit**-2.0)
    ph = np.exp(2.0j * np.pi * np.outer(t_n, k))
    x = dFT * np.conj(mFT) * ph * w  # (nchan, nharm)
    C = np.sum(x.real, axis=-1)
    S = np.sum(np.abs(mFT) ** 2.0 * w, axis=-1)
    S = np.maximum(S, 1e-300)
    return k, t_n, x, C, S


def chi2_prime_ref(theta, dFT, mFT, w, freqs, P, nu_fit):
    _, _, _, C, S = _objective_pieces(theta, dFT, mFT, w, freqs, P, nu_fit)
    return -np.sum(C**2.0 / S)


def chi2_prime_grad_ref(theta, dFT, mFT, w, freqs, P, nu_fit):
    k, _, x, C, S = _objective_pieces(theta, dFT, mFT, w, freqs, P, nu_fit)
    # dC_n/dt_n = -2 pi sum_k k Im(x_nk)... d/dt of Re[x e^{2pi i k t}]
    dC_dt = -2.0 * np.pi * np.sum(k * x.imag, axis=-1)
    dchi_dt = -2.0 * C / S * dC_dt
    dt_dphi = np.ones_like(freqs)
    dt_dDM = (Dconst / P) * (freqs**-2.0 - nu_fit**-2.0)
    return np.array([np.sum(dchi_dt * dt_dphi), np.sum(dchi_dt * dt_dDM)])


def fit_portrait_numpy(port, model, noise_stds, freqs, P, nu_fit, DM0=0.0):
    """(phi, DM) fit of one portrait; returns a dict with phi, DM,
    phi_err, DM_err, nu_zero, chi2, nfeval."""
    port = np.asarray(port, float)
    model = np.asarray(model, float)
    freqs = np.asarray(freqs, float)
    nbin = port.shape[-1]
    dFT = np.fft.rfft(port, axis=-1)
    mFT = np.fft.rfft(model, axis=-1)
    errs_F = np.asarray(noise_stds) * np.sqrt(nbin / 2.0)
    w = np.where(errs_F > 0, errs_F**-2.0, 0.0)[:, None] * np.ones(
        dFT.shape[-1]
    )
    w[:, 0] *= F0_fact

    # dense CCF phase seed at DM0
    k = np.arange(dFT.shape[-1])
    t_n = (Dconst * DM0 / P) * (freqs**-2.0 - nu_fit**-2.0)
    x = np.sum(dFT * np.conj(mFT) * np.exp(2.0j * np.pi * np.outer(t_n, k)) * w, axis=0)
    ccf = np.fft.irfft(x, n=2 * nbin)
    phi0 = np.argmax(ccf) / (2.0 * nbin)
    if phi0 >= 0.5:
        phi0 -= 1.0

    nfev = [0]

    def f(theta):
        nfev[0] += 1
        return chi2_prime_ref(theta, dFT, mFT, w, freqs, P, nu_fit)

    def g(theta):
        return chi2_prime_grad_ref(theta, dFT, mFT, w, freqs, P, nu_fit)

    res = opt.minimize(
        f, np.array([phi0, DM0]), jac=g, method="trust-ncg",
        hess=lambda th: _num_hess(f, th),
        options={"gtol": 1e-10, "maxiter": 200},
    )
    phi, DM = res.x
    H = _num_hess(f, res.x)
    cov = 2.0 * np.linalg.inv(H)
    phi_err, DM_err = np.sqrt(np.abs(np.diag(cov)))
    return dict(
        phi=((phi + 0.5) % 1.0) - 0.5,
        DM=DM,
        phi_err=phi_err,
        DM_err=DM_err,
        covariance=cov,
        chi2=np.sum(np.abs(dFT) ** 2 * w) + res.fun,
        nfeval=nfev[0],
    )


def _num_hess(f, x, eps=None):
    """Central finite-difference Hessian (the reference oracle does not
    need to be fast)."""
    x = np.asarray(x, float)
    n = len(x)
    if eps is None:
        eps = np.maximum(np.abs(x), [1e-6, 1e-7]) * 1e-5 + 1e-12
    H = np.zeros((n, n))
    for i in range(n):
        for j in range(i, n):
            ei = np.zeros(n)
            ej = np.zeros(n)
            ei[i] = eps[i]
            ej[j] = eps[j]
            H[i, j] = H[j, i] = (
                f(x + ei + ej) - f(x + ei - ej) - f(x - ei + ej) + f(x - ei - ej)
            ) / (4.0 * eps[i] * eps[j])
    return H
