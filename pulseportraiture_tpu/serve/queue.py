"""Admission queue + request objects for the TOA serving loop.

The queue is the BACKPRESSURE story of the service (ISSUE 8): it is
bounded in ARCHIVES (the unit of admission work — one archive is one
load + prepare + bucket fill), and a submit that would exceed the
bound raises :class:`ServeRejected` LOUDLY instead of absorbing
unbounded host memory.  Clients retry, shed load, or raise
``config.serve_queue_depth``; the server never silently queues more
than it agreed to.  Device-side concurrency is bounded separately by
the executor's ``max_inflight``/``pipeline_depth`` — the admission
bound only governs what the host has promised to prepare.

A :class:`ServeRequest` is one client submission: a batch of archives
measured against one template with one option set.  Its lifecycle is
submit -> admit (the server loads + buckets its archives; subints from
different requests coalesce into shared fused dispatches) -> done (the
per-request ``.tim``/result is demultiplexed back out).  ``result()``
blocks the submitting client; the server thread resolves it.
"""

import itertools
import threading
import time

__all__ = ["ServeRejected", "ServeRequest", "AdmissionQueue"]


class ServeRejected(RuntimeError):
    """A submission the server did NOT accept: the admission queue is
    at capacity (backpressure — ``retryable`` is True, retry later or
    shed load) or the server is stopping/closed (``retryable`` False —
    resubmitting can never succeed).  Nothing about the request was
    enqueued."""

    def __init__(self, msg, retryable=False):
        super().__init__(msg)
        self.retryable = bool(retryable)


class ServeRequest:
    """One client submission to the serving loop.

    datafiles: archive paths (or a metafile path); modelfile: the
    template; options: make_wideband_lane kwargs (fit_scat=, DM0=,
    print_flux=, ...) — requests sharing (modelfile, options) share a
    lane and therefore coalesce into the same fused buckets; tim_out:
    optional path the server writes this request's .tim to (archive
    order, completion sentinels — byte-identical to the one-shot
    driver's checkpoint).  The server fills the bookkeeping fields;
    clients call :meth:`result`.
    """

    _ids = itertools.count()

    def __init__(self, datafiles, modelfile, options=None, tim_out=None,
                 name=None):
        from ..pipeline.toas import _is_metafile, _read_metafile

        if isinstance(datafiles, str):
            self.datafiles = (_read_metafile(datafiles)
                              if _is_metafile(datafiles)
                              else [datafiles])
        else:
            self.datafiles = list(datafiles)
        if not self.datafiles:
            raise ValueError("ServeRequest: empty datafile list")
        self.modelfile = str(modelfile)
        self.options = dict(options or {})
        self.tim_out = tim_out
        self.name = str(name) if name is not None else \
            f"req{next(ServeRequest._ids)}"
        # lifecycle timestamps (monotonic): submit by the queue, admit/
        # done by the server — what the request_done latency split and
        # the pptrace serve section report
        self.t_submit = None
        self.t_admit = None
        self.t_done = None
        # server-side demux state: archive position -> (meta, assembly)
        self.meta = {}
        self.assembled = {}
        self.n_skipped = 0
        self.all_admitted = False
        # archive positions already sent through the quality-gated
        # zap-and-refit loop (server-side; the EXACTLY-ONCE bound —
        # a position in here never refits again)
        self.refit_pos = set()
        self._event = threading.Event()
        self._result = None
        self._error = None

    def done(self):
        return self._event.is_set()

    def wait(self, timeout=None):
        """Block up to ``timeout`` seconds for the server to resolve
        this request; True when resolved (result() will not block),
        False on timeout.  Unlike :meth:`result` this never raises —
        it is the polling primitive remote transports build on."""
        return self._event.wait(timeout)

    def result(self, timeout=None):
        """Block until the server resolves this request; returns the
        per-request DataBunch (TOA_list, order, DM0s, DeltaDM_means/
        errs, tim_out) or raises the server-side failure."""
        if not self._event.wait(timeout):
            raise TimeoutError(
                f"{self.name}: no result within {timeout} s")
        if self._error is not None:
            raise self._error
        return self._result


class AdmissionQueue:
    """Bounded, thread-safe request queue feeding one serving loop.

    ``submit`` (any client thread) appends or REJECTS — it never
    blocks, so a client can tell load-shedding from slowness.  ``get``
    (the server thread) pops with a timeout so the serving loop keeps
    ticking its deadline flushes while idle.  The archive-count
    accounting is released as the server admits each archive
    (:meth:`release`), i.e. the bound covers submitted-but-not-yet-
    prepared work.
    """

    def __init__(self, max_pending):
        self.max_pending = max(1, int(max_pending))
        self._cv = threading.Condition()
        self._q = []
        self._pending = 0
        self._closed = False

    def __len__(self):
        with self._cv:
            return len(self._q)

    @property
    def pending_archives(self):
        with self._cv:
            return self._pending

    def submit(self, request):
        """Enqueue or raise ServeRejected (queue full / closed)."""
        n = len(request.datafiles)
        with self._cv:
            if self._closed:
                raise ServeRejected(
                    "serving queue is closed (server stopping); "
                    f"request {request.name!r} rejected")
            if n > self.max_pending:
                # could NEVER fit, even into an idle queue: terminal,
                # not retryable — a retrying client would spin forever
                raise ServeRejected(
                    f"request {request.name!r} holds {n} archives, "
                    f"more than the whole queue depth "
                    f"{self.max_pending}; split it or raise "
                    "config.serve_queue_depth")
            if self._pending + n > self.max_pending:
                raise ServeRejected(
                    f"admission queue full: {self._pending} archive(s) "
                    f"pending + {n} submitted > queue depth "
                    f"{self.max_pending} (config.serve_queue_depth / "
                    "PPT_SERVE_QUEUE_DEPTH); retry later",
                    retryable=True)
            self._pending += n
            request.t_submit = time.monotonic()
            self._q.append(request)
            self._cv.notify()

    def get(self, timeout=None):
        """Pop the oldest request, waiting up to ``timeout`` seconds;
        None on timeout (or closed-and-empty)."""
        with self._cv:
            if not self._q and not self._closed:
                self._cv.wait(timeout)
            return self._q.pop(0) if self._q else None

    def release(self, n=1):
        """Return ``n`` archives' worth of admission credit (the
        server admitted or abandoned them)."""
        with self._cv:
            self._pending = max(0, self._pending - int(n))

    def close(self):
        """Refuse all further submissions (graceful-drain entry);
        already-queued requests still drain."""
        with self._cv:
            self._closed = True
            self._cv.notify_all()

    def drain(self):
        """Pop everything still queued (abort path) — the caller fails
        these requests loudly."""
        with self._cv:
            out, self._q = self._q, []
            return out
