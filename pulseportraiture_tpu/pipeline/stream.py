"""Cross-archive streaming TOA measurement — the at-scale driver.

GetTOAs dispatches one batched fit per archive; on the tunneled TPU
runtime each dispatch has a ~100 ms floor, so a 1000-archive campaign
with modest per-archive subint counts is dispatch-bound, not
compute-bound.  This driver instead POOLS ok subints across archives
into shape buckets — keyed by (nchan, nbin, channel-frequency layout,
effective fit flags, and the template period when the template depends
on P) — and fires one large fused dispatch per full bucket.  Three
levels of overlap keep every resource busy:

- archive IO runs ahead of the consumer on prefetch threads;
- dispatches are ASYNCHRONOUS and MULTI-DEVICE — full buckets are
  dealt round-robin across ``stream_devices`` local chips (default:
  all of them), each with its own dispatch worker thread and a
  bounded in-flight queue of up to ``max_inflight`` pending batches;
  the host drains whichever device's oldest dispatch is ready, so a
  slow chip never stalls its siblings, and .tim checkpoints are
  written in archive order so output is digit-identical to the
  single-device lane;
- in raw mode the host never decodes the data at all: the undecoded
  DATA column ships to the accelerator as-is (2-4x fewer bytes than
  f32 — host->device bandwidth is the campaign bottleneck) and ONE
  jitted program does decode -> baseline -> noise -> S/N -> nu_fit ->
  fit, returning a single packed per-subint result array (one small
  device->host pull per bucket);
- each device's h2d copies run on their own COPY worker, double-
  buffered against the device's FIT worker (_DevicePipeline,
  config.stream_pipeline_depth): bucket N+1's bytes move while bucket
  N's fused program executes, so the link and the chip stay busy
  simultaneously (h2d_start/h2d_done trace events measure it).

Raw mode is UNIVERSAL over the PSRFITS sample types (int16, unsigned/
signed byte, float32, and sub-byte NBIT=1/2/4 packed samples — which
ship their PACKED bytes and are bit-plane-unpacked on device, 32x
fewer bytes than decoded f64 for a 2-bit archive —
ops/decode.RAW_CODES), general FITS column TSCAL/TZERO scaling (two
scalars ride the payload and fold into the device affine), and
polarization states:
npol == 1 ships as-is, IQUV ships only its Stokes-I plane (a host
index, no extra bytes), AA+BB/Coherence ship their two summand pols
and the device decode reduces them to Stokes I.  Dedispersed-on-disk
archives are re-dispersed ON DEVICE by the stored DM (host-wrapped
f64 turns, matmul-DFT rotation).  The remaining fallbacks to the
decoded (host-side load_data) lane: tscrunch, misaligned sub-byte pol
planes, packed + FITS-scaled columns, and the PPT_RAW_SUBBYTE escape
hatch.  An optional LOSSLESS transport codec
(config.transport_compress; io/blockcodec.py) can width-reduce
integer payloads further on the copy worker, chosen per dispatch by a
cost model fed from the live h2d telemetry — .tim output is
digit-identical compressed or not.

Scope: campaign configurations — wideband (phi[, DM[, GM]]) fits,
scattering (fit_scat/log10_tau/scat_guess/fix_alpha as in GetTOAs),
flux estimates (print_flux), and instrumental-response kernels
(instrumental_response_dict, incl. per-archive DM smearing); the
narrowband per-channel mode streams via stream_narrowband_TOAs
(pptoas --stream --narrowband).  On fast backends
(config.use_fast_fit — TPU default) EVERY bucket is complex-free:
no-scattering buckets run the 3-moment fast path, scattering buckets
the fused analytic _cgh_scatter lane, sharing the matmul-DFT front end;
instrumental-response kernels ship as split real arrays (complex
buffers cannot cross some tunneled transports).  Subints with a single
usable channel are demoted to phase-only buckets (the degenerate-
geometry fallback, pptoas.py:519-527).

The reference has no analogue (strictly sequential archive loop,
pptoas.py:258); this is new capability enabled by the batched engine.
"""

import os
import threading
import time
from contextlib import nullcontext as _null_ctx
from functools import lru_cache

import jax
import jax.numpy as jnp
import numpy as np

from ..config import Dconst, scattering_alpha
from ..fit.portrait import (FitFlags, _fast_batch_fn, estimate_tau_batch,
                            fit_portrait_batch, fit_portrait_batch_fast,
                            use_bf16_cross_spectrum, use_fast_fit_default)
from ..io.psrfits import read_archive
from ..io.tim import TOA, write_TOAs
from ..obs.metrics import record_h2d
from ..ops.noise import get_SNR, get_noise_PS
from ..telemetry import NULL_TRACER, finite, log, resolve_tracer
from ..utils.bunch import DataBunch
from .models import TemplateModel
from .toas import (_is_metafile, _iter_archives, _read_metafile,
                   _validate_scat_guess, delta_dm_stats,
                   doppler_corrected_DM_GM, effective_fit_flags,
                   load_for_toas, scat_seed_tau0, scat_time_flags,
                   scattering_toa_flags, snr_weighted_nu_fit)


# Per-archive completion sentinel in incremental .tim checkpoints: a
# comment line (readers skip 'C ' lines) appended AFTER an archive's
# TOA lines, so "last sentinel" marks the last durably-complete
# archive — everything after it is a partial tail from an interrupted
# writer and is dropped on resume.
_DONE_PREFIX = "C ppt-done "

# Checkpoint-staleness horizon: .tim checkpoint writes are in ARCHIVE
# order (so content is digit-identical for any device count), which
# means an early archive stuck in a never-filling rare-shape bucket
# would defer every later completed archive's durability.  Once the
# oldest archive with undispatched subints lags this many prepared
# archives behind, ALL pending buckets are force-flushed.  The trigger
# depends only on the deterministic fill/launch sequence — never on
# completion timing or device count — so dispatch composition (and
# with it output digit-identity) is unchanged across device counts.
CKPT_STALENESS_HORIZON = 8


def checkpoint_completed(path):
    """Archive paths (absolute) recorded complete in a .tim checkpoint
    (empty set for a missing file).  A sentinel only counts when its
    line is newline-terminated: a writer killed mid-sentinel leaves a
    truncated final line whose path could still prefix-match — it is
    part of the torn tail, not a durable completion record."""
    if not path or not os.path.exists(path):
        return set()
    with open(path) as f:
        return {os.path.abspath(line[len(_DONE_PREFIX):].strip())
                for line in f
                if line.startswith(_DONE_PREFIX) and line.endswith("\n")}


def sanitize_checkpoint(path):
    """Truncate a .tim checkpoint after its last completion sentinel,
    dropping the partial tail an interrupted (or killed) writer left.
    The rewrite is ATOMIC (temp file + os.replace): resume runs are by
    definition crash-prone, and an in-place truncate-then-write would
    lose every completed archive to a second kill — or show a
    concurrent reader an empty file mid-rewrite.  Returns the
    completed-archive set (absolute paths)."""
    if not path or not os.path.exists(path):
        return set()
    with open(path) as f:
        lines = f.readlines()
    last = -1
    done = set()
    for i, line in enumerate(lines):
        # same newline rule as checkpoint_completed: an unterminated
        # final "sentinel" is a torn write and belongs to the tail
        if line.startswith(_DONE_PREFIX) and line.endswith("\n"):
            last = i
            done.add(os.path.abspath(line[len(_DONE_PREFIX):].strip()))
    if last + 1 < len(lines):
        tmp = path + ".ppt-sanitize"
        with open(tmp, "w") as f:
            f.writelines(lines[:last + 1])
        os.replace(tmp, path)
    return done


class _Bucket:
    """Pending subints sharing one (layout, flags, kind) key.

    kind 'dec': rows are decoded float ports; noise/nu_fit/theta0 are
    computed on host (round-1 lane).  kind 'raw': rows are undecoded
    wire samples (raw_code names the sample type — ops/decode
    RAW_CODES) with per-channel scl/offs; everything downstream
    happens in the fused device program.  pol_sum=True raw rows carry
    the TWO summand pols of an AA+BB/Coherence archive ((2, nchan,
    nbin) each) and the device decode reduces them to Stokes I."""

    def __init__(self, freqs, nbin, modelx, flags, kind="dec",
                 ir_FT=None, raw_code="i16", pol_sum=False,
                 col_scaled=False):
        self.freqs = freqs          # (nchan,)
        self.nbin = int(nbin)
        self.modelx = modelx        # (nchan, nbin) template
        self.flags = flags          # effective FitFlags tuple
        self.kind = kind
        self.key = None             # executor bucket key (set at admit)
        self.lane = None            # the lane whose launch/scatter/
        # assemble hooks own this bucket's subints — per-bucket so ONE
        # executor can serve several lanes (the serving loop feeds one
        # warm executor from many concurrent requests/templates)
        self.raw_code = raw_code    # 'raw': wire sample type
        self.pol_sum = bool(pol_sum)  # 'raw': device pol0+pol1 sum
        self.col_scaled = bool(col_scaled)  # 'raw': general FITS
        # column TSCAL/TZERO ride the payload (its own compiled
        # program: one extra fused multiply-add in the decode)
        self.ir_FT = ir_FT          # (nchan, nharm) complex or None
        self._hwin = None
        self._hwin_key = object()   # never equals a config value
        self.ports = []             # 'dec': (nchan, nbin) float
        self.raw = []               # 'raw': (nchan, nbin) wire samples
        # ((plane_bytes,) packed bytes for sub-byte codes)
        self.scl = []               # 'raw': (nchan,) f32
        self.offs = []              # 'raw': (nchan,) f32
        self.tscal = []             # 'raw'+col_scaled: scalar TSCAL
        self.tzero = []             # 'raw'+col_scaled: scalar TZERO
        self.dedisp = []            # 'raw': (DM, nu0) to re-disperse by
        self.noise = []             # 'dec': (nchan,)
        self.masks = []             # each (nchan,)
        self.Ps = []
        self.nu_fits = []           # 'dec' only
        self.theta0 = []            # 'dec': each (5,)
        self.DM_guess = []          # 'raw': scalar per subint
        self.dfs = []               # doppler factor per subint (the
        # in-stream postfit cut rotates by the doppler-corrected DM)
        self.owners = []            # (archive_index, isub)

    def harmonic_window(self):
        """Per-bucket memoized harmonic window: the ~10 ms host rfft
        of the template runs once per bucket per knob value — not per
        dispatch, and not at all for complex-engine-only runs (only
        the fast lanes call this) — while mid-run config toggles still
        take effect (the memo keys on the knob)."""
        from .. import config
        from ..fit.portrait import resolve_harmonic_window

        key = getattr(config, "fit_harmonic_window", None)
        if key != self._hwin_key:
            self._hwin = resolve_harmonic_window(None, self.modelx,
                                                 self.nbin)
            self._hwin_key = key
        return self._hwin

    def __len__(self):
        return len(self.owners)

    def clear(self):
        for lst in (self.ports, self.raw, self.scl, self.offs,
                    self.tscal, self.tzero, self.dedisp,
                    self.noise, self.masks, self.Ps, self.nu_fits,
                    self.theta0, self.DM_guess, self.dfs,
                    self.owners):
            lst.clear()


def _bucket_shape(b):
    """The dispatch-event shape string for a bucket: layout x payload
    kind (raw buckets name their wire sample type and pol reduction —
    each is its own compiled program) x effective flag bits.  This is
    the trace key pptrace groups compiles by AND the manifest entry
    ``utils/device.warmup_from_manifest`` compiles from, so
    :func:`parse_shape_key` must stay its exact inverse."""
    shape = f"{len(b.freqs)}x{b.nbin}:{b.kind}"
    if b.kind == "raw":
        shape += f":{b.raw_code}"
        if b.pol_sum:
            shape += ":sum2"
        if b.col_scaled:
            shape += ":tz"
    if b.flags:
        shape += ":" + "".join("1" if f else "0" for f in b.flags)
    return shape


def parse_shape_key(shape):
    """Inverse of :func:`_bucket_shape`: parse a dispatch-event shape
    string back into the bucket geometry an AOT warmup pass needs to
    rebuild the compiled program (nchan, nbin, kind, raw_code, pol_sum,
    flags).  flags is None for flagless (narrowband) shapes.  Raises
    ValueError on anything it cannot round-trip — warmup must not
    silently compile the wrong program."""
    from ..ops.decode import RAW_CODES

    parts = shape.split(":")
    try:
        nchan, nbin = (int(v) for v in parts[0].split("x"))
        kind = parts[1]
    except (ValueError, IndexError):
        raise ValueError(f"unparseable dispatch shape {shape!r}")
    if kind not in ("raw", "dec") or nchan < 1 or nbin < 1:
        raise ValueError(f"unparseable dispatch shape {shape!r}")
    raw_code, pol_sum, col_scaled, flags = "i16", False, False, None
    for tok in parts[2:]:
        if kind == "raw" and tok == "sum2":
            pol_sum = True
        elif kind == "raw" and tok == "tz":
            col_scaled = True
        elif kind == "raw" and tok in RAW_CODES:
            raw_code = tok
        elif tok and set(tok) <= {"0", "1"}:
            flags = tuple(c == "1" for c in tok)
        else:
            raise ValueError(
                f"unknown token {tok!r} in dispatch shape {shape!r}")
    return dict(nchan=nchan, nbin=nbin, kind=kind, raw_code=raw_code,
                pol_sum=pol_sum, col_scaled=col_scaled, flags=flags)


def bucket_pad_to(nchan):
    """Resolve ``config.bucket_pad`` to the padded channel count for a
    bucket layout (ROADMAP item 5: coarsen the bucket lattice).  Every
    distinct nchan is a distinct XLA compile; padding layouts up to the
    next power of two with zero-weight channels collapses the lattice
    so a fleet's shape diversity costs log2 as many compiles.  False
    (default): exact shapes (bit-stable outputs across releases);
    'auto': pad on TPU backends (where the compile cost dominates);
    True: always pad.  Masked pad channels contribute exactly zero to
    every fit statistic, so .tim output is digit-identical padded vs
    exact (tests/test_serve.py guards it)."""
    from .. import config

    from ..tune.capability import resolve_auto

    v = getattr(config, "bucket_pad", False)
    if isinstance(v, str) and v.strip().lower() != "auto":
        raise ValueError(
            f"config.bucket_pad must be False, 'auto' or True; "
            f"got {v!r}")
    v = resolve_auto("bucket_pad", v, label="config.bucket_pad")
    if not v or nchan <= 1:
        return int(nchan)
    return 1 << (int(nchan) - 1).bit_length()


def resolve_stream_devices(value=None):
    """Resolve a ``stream_devices`` knob value to the list of local
    jax devices the streaming drivers dispatch across.

    None reads ``config.stream_devices``; 'auto' means every local
    device of the default backend; an int N means the first N local
    devices (loud error when N exceeds the local count — a silent
    clamp would quietly invalidate a scaling A/B); an explicit device
    sequence passes through."""
    from .. import config

    if value is None:
        value = getattr(config, "stream_devices", "auto")
    devs = jax.local_devices()
    if isinstance(value, str):
        if value.strip().lower() == "auto":
            return list(devs)
        try:
            value = int(value)
        except ValueError:
            raise ValueError(
                "stream_devices must be 'auto', a positive device "
                f"count, or a device sequence; got {value!r}")
    if isinstance(value, (int, np.integer)):
        n = int(value)
        if n < 1:
            raise ValueError(
                f"stream_devices must be >= 1, got {n}")
        if n > len(devs):
            raise ValueError(
                f"stream_devices={n} exceeds the {len(devs)} local "
                f"device(s) of backend {jax.default_backend()!r}")
        return list(devs[:n])
    devs = list(value)
    if not devs:
        raise ValueError("stream_devices: empty device sequence")
    return devs


class _StreamExecutor:
    """The campaign scaffolding shared by stream_wideband_TOAs and
    stream_narrowband_TOAs — previously duplicated per driver (VERDICT
    r3 weak #3): archive iteration with prefetch and skip-and-continue,
    bucket fill/flush, the multi-device round-robin dispatch queues,
    per-archive completion accounting, incremental .tim checkpointing
    with completion sentinels (and resume), and the fail-fast executor
    shutdown.  A LANE supplies the per-driver physics as four hooks:

      prepare(iarch, datafile, d, ok) -> (m, per_subint) or None
          m: the minimal per-archive record TOA assembly needs;
          per_subint: [(bucket_key, bucket_factory, fill)] — fill(b)
          appends one subint's payload AND its (iarch, isub) owner.
          None skips the archive (prepare prints why).
      launch(bucket, pipeline, seq) -> (handle, owners, extra) or
          None — admits one fused dispatch into ``pipeline`` (the
          device's two-stage copy->fit _DevicePipeline; ``seq`` is
          the trace sequence its h2d events stamp), snapshots owners,
          and clears the bucket; handle is the fit-stage Future.
      scatter(out, owners, extra, results) -> None
          unpacks one dispatch's packed output into per-owner records.
      assemble(m, results) -> tuple whose first element is the TOA list
          (what the incremental checkpoint writes).

    MULTI-DEVICE dispatch (ISSUE 4): full buckets are dealt round-robin
    across ``stream_devices`` (config.stream_devices: 'auto' = all
    local devices).  Each device owns a bounded in-flight deque (the
    bound is EXACT — a queue never exceeds max_inflight) and a
    two-stage TRANSFER PIPELINE (ISSUE 6, _DevicePipeline): the h2d
    copy is the campaign bottleneck on tunneled runtimes, so each
    device runs a dedicated copy worker double-buffered
    (config.stream_pipeline_depth) against its fit worker — bucket
    N+1's bytes move while bucket N's program runs, and copies to
    different devices overlap each other.  The drain policy always
    services ready dispatches first, on whichever device they
    completed, so a slow chip never stalls its siblings; when every
    queue is full the host blocks on the FIRST completion among the
    oldest dispatches.  Results stay keyed by (iarch, isub) owners and
    checkpoints are written in ARCHIVE ORDER, so campaign output —
    .tim content included — is digit-identical to the single-device
    lane regardless of completion order; a rare-shape straggler
    archive can defer those in-order writes by at most
    CKPT_STALENESS_HORIZON prepared archives before every pending
    bucket force-flushes, so an interrupted campaign still keeps its
    completed work on disk.

    run() returns (meta, assembled) with assembled keyed by iarch; the
    caller finishes lane-specific summaries from those.

    DRIVER-AGNOSTIC FEEDING (ISSUE 8): run() is now a thin client of
    the incremental interface — ``admit()`` prepares one loaded
    archive into buckets (flushing any that fill), ``flush_stale()``
    launches partial buckets past a deadline (the serving loop's
    continuous-batching policy), ``flush_all``/``drain_all``/
    ``finalize`` end a stream, and the ``on_launch``/
    ``on_archive_done`` hooks let an owner demultiplex completions.
    A long-lived owner (serve/server.ToaServer) constructs ONE
    executor with ``service=True`` and no datafiles, keeps it warm
    across requests (jit caches, device pipelines, compile cache all
    survive), and passes a per-request ``lane`` to each admit — lanes
    ride the buckets and in-flight records, so subints from different
    requests coalesce into shared dispatches whenever their bucket
    keys match.
    """

    def __init__(self, lane, datafiles, loader, nsub_batch,
                 max_inflight=None, prefetch=True, tim_out=None,
                 resume=False, skip_archives=None, quiet=False,
                 stream_devices=None, tracer=None,
                 pipeline_depth=None, service=False):
        from collections import deque

        from .. import config
        from ..utils.device import enable_compile_cache

        # persistent compilation cache (config.compile_cache_dir /
        # PPT_COMPILE_CACHE): a no-op when unset; applied here so any
        # campaign driver benefits without its own wiring
        enable_compile_cache()
        self.lane = lane
        self.nsub_batch = int(nsub_batch)
        if max_inflight is None:
            max_inflight = config.stream_max_inflight
        self.max_inflight = max(1, int(max_inflight))
        if pipeline_depth is None:
            pipeline_depth = config.stream_pipeline_depth
        self.pipeline_depth = max(1, int(pipeline_depth))
        self.prefetch = prefetch
        self.tim_out = tim_out
        self.quiet = quiet
        self.tracer = NULL_TRACER if tracer is None else tracer
        done = {os.path.abspath(f) for f in (skip_archives or ())}
        if tim_out:
            if resume:
                done |= sanitize_checkpoint(tim_out)
            else:
                # fresh checkpoint: a rerun must not append onto a
                # previous campaign's lines
                open(tim_out, "w").close()
        if done:
            skipped = [f for f in datafiles
                       if os.path.abspath(f) in done]
            datafiles = [f for f in datafiles
                         if os.path.abspath(f) not in done]
            if skipped:
                if self.tracer.enabled:
                    self.tracer.emit("resume_skip",
                                     n_skipped=len(skipped),
                                     n_remaining=len(datafiles))
                log(f"Resuming: {len(skipped)} archive(s) already "
                    f"complete in checkpoints, {len(datafiles)} "
                    "to go", quiet=quiet)
        self.datafiles = datafiles
        self.loader = loader
        # service mode (a long-lived queue-fed owner): per-archive
        # bookkeeping must stay O(live work), so the run()-only growing
        # lists (meta, checkpoint order) are skipped and the owner
        # calls forget() as requests complete
        self.service = bool(service)
        self.on_launch = None        # hook(seq, owners, pad) per dispatch
        self.on_archive_done = None  # hook(iarch, m, out) per assembly
        self.devices = resolve_stream_devices(stream_devices)
        self.buckets = {}
        self._bucket_t0 = {}  # bucket key -> first pending fill (mono s)
        self._lane_by_iarch = {}
        self.results = {}
        self.meta = []
        self.meta_by_iarch = {}
        self.remaining = {}
        self.assembled = {}
        self.in_flight = [deque() for _ in self.devices]
        # ONE transfer pipeline PER DEVICE (copy worker + fit worker):
        # within a device h2d copies serialize on its link anyway (a
        # single copy thread keeps that device's dispatch order
        # deterministic), copies to DIFFERENT devices overlap
        # (device_put releases the GIL), and the copy/fit stage split
        # double-buffers each device's link against its own in-flight
        # compute.  The inflight_fn closure binds THIS device's deque
        # so the copy worker can flag h2d-vs-fit overlap without any
        # executor lock: a dispatch counts only while UNFINISHED
        # (pending future or still-running device program — the same
        # readiness test the drain uses), and only when EARLIER than
        # the copy (seq is monotonic): the copy's own record and any
        # already-admitted successor still queued BEHIND this copy on
        # the single copy worker are trivially unfinished but represent
        # no device compute, and counting them would flatter the
        # overlap stat at depth >= 2; list(q) snapshots the deque
        # atomically under the GIL against main-thread appends.
        self.pipelines = [
            _DevicePipeline(dev, i, self.pipeline_depth, self.tracer,
                            (lambda seq, q=self.in_flight[i]: any(
                                r[3] < seq
                                and not _StreamExecutor._head_ready(r)
                                for r in list(q))))
            for i, dev in enumerate(self.devices)]
        self._rr = 0
        # iarch -> subints not yet launched; entries leave at zero so
        # the staleness scan in run() stays O(live archives), not
        # O(campaign)
        self.undispatched = {}
        self._prep_idx = {}  # iarch -> prepared-archive sequence no.
        self.nfit = 0
        self.fit_duration = 0.0      # blocked on dispatch completion
        self.scatter_duration = 0.0  # host-side unpack of results
        self.devices_used = set()
        self.peak_inflight = 0
        self.dispatch_counts = [0] * len(self.devices)
        self._warm = set()           # (shape, idev) pairs dispatched
        # checkpoint bookkeeping: archives in ACCEPTED order, plus the
        # index of the next one to write (in-order emission)
        self._ckpt_order = []
        self._ckpt_next = 0

    def _checkpoint(self, m, out):
        write_TOAs(out[0], outfile=self.tim_out, append=True)
        with open(self.tim_out, "a") as fh:
            fh.write(_DONE_PREFIX + os.path.abspath(m.datafile) + "\n")

    def _ckpt_flush(self):
        """Write completed archives to the checkpoint strictly in
        archive order: completion order varies with device count and
        chip speed, but the .tim content must not."""
        if not self.tim_out:
            return
        while self._ckpt_next < len(self._ckpt_order):
            ia = self._ckpt_order[self._ckpt_next]
            if ia not in self.assembled:
                break
            m, out = self.meta_by_iarch[ia], self.assembled[ia]
            self._checkpoint(m, out)
            if self.tracer.enabled:
                # lag: archives PREPARED after this one by the time
                # its in-order write landed — the straggler signal the
                # pptrace stall section ranks on
                self.tracer.emit(
                    "ckpt_flush", iarch=ia, datafile=m.datafile,
                    n_toas=len(out[0]),
                    lag=len(self._ckpt_order) - 1 - self._prep_idx[ia])
            self._ckpt_next += 1

    @staticmethod
    def _head_ready(rec):
        """True when draining this record will not block: the dispatch
        future resolved AND (jax async dispatch!) the device program
        behind its output has finished.  Future.done() alone is not
        enough — the jitted call returns as soon as the work is
        enqueued, so a 'done' future can still hide a running program
        on a slow device, and treating it as ready would stall the
        ready-first drain on exactly the chip it is meant to route
        around."""
        h = rec[0]
        if hasattr(h, "done"):
            if not h.done():
                return False
            if h.exception() is not None:
                return True  # drain propagates the failure
            h = h.result()
        ready = getattr(h, "is_ready", None)
        return bool(ready()) if callable(ready) else True

    def _drain_head(self, idev):
        """Drain device idev's oldest dispatch (blocking on it)."""
        t0 = time.time()
        handle, owners, extra, seq, lane = self.in_flight[idev].popleft()
        out = handle.result() if hasattr(handle, "result") else handle
        # wait for the device program itself, not just the dispatch
        # thread: the split below must charge device time to
        # fit_duration and ONLY the host-side unpack to
        # scatter_duration (the old single count over-reported "blocked
        # on device" by the whole host scatter)
        try:
            out = jax.block_until_ready(out)
        except TypeError:
            pass  # non-array handle (already host data)
        wait_s = time.time() - t0
        self.fit_duration += wait_s
        t1 = time.time()
        lane.scatter(out, owners, extra, self.results)
        scat_s = time.time() - t1
        self.scatter_duration += scat_s
        if self.tracer.enabled:
            # timestamps only around the two calls above, which block
            # regardless of telemetry — no extra host sync
            self.tracer.emit("drain", seq=seq, device=idev,
                             wait_s=round(wait_s, 6),
                             scatter_s=round(scat_s, 6))
            # per-TOA quality rollup for this dispatch (dict-shaped
            # results, i.e. the wideband lane; the narrowband lane
            # packs per-channel arrays and already flags snr/gof per
            # TOA line)
            snrs, gofs, nfevs = [], [], []
            for ow in owners:
                r = self.results.get(ow)
                if isinstance(r, dict) and "snr" in r:
                    # finite(): degenerate fits yield NaN snr/chi2 and
                    # json.dumps would write bare NaN tokens strict
                    # JSON consumers reject — map them to null
                    snrs.append(finite(r["snr"], 3))
                    gofs.append(finite(float(r["chi2"])
                                       / max(float(r["dof"]), 1.0), 4))
                    nfevs.append(int(r["nfeval"]))
            if snrs:
                self.tracer.emit("quality", seq=seq, snr=snrs,
                                 gof=gofs, nfev=nfevs)
        touched = set()
        for iarch, _ in owners:
            if iarch in self.remaining:
                self.remaining[iarch] -= 1
            touched.add(iarch)
        for ia in touched:
            # assemble completed archives immediately (host memory
            # stays O(bucket)); the checkpoint WRITE still waits for
            # archive order
            if self.remaining.get(ia) == 0 and ia not in self.assembled:
                m = self.meta_by_iarch[ia]
                out = self._lane_by_iarch.get(ia, self.lane).assemble(
                    m, self.results)
                self.assembled[ia] = out
                if self.tracer.enabled:
                    self.tracer.emit("archive_done", iarch=ia,
                                     datafile=m.datafile)
                # per-subint records fold into the assembly; dropping
                # them keeps host memory O(bucket)
                for isub in m.ok:
                    self.results.pop((ia, int(isub)), None)
                self._ckpt_flush()
                if self.on_archive_done is not None:
                    # the owner's demux hook (serving loop): runs on
                    # the draining thread, AFTER the per-subint records
                    # folded, so the owner may forget() this archive
                    self.on_archive_done(ia, m, out)

    def _drain_ready(self):
        """Non-blocking: drain every dispatch whose handle has already
        completed, oldest-first per device.  Returns the count."""
        n = 0
        for idev, q in enumerate(self.in_flight):
            while q and self._head_ready(q[0]):
                self._drain_head(idev)
                n += 1
        return n

    def _drain_any(self):
        """Drain at least one dispatch: everything already ready
        first; otherwise wait for the FIRST completion among the
        per-device oldest dispatches — unresolved futures via
        cf.wait, resolved-but-still-running device programs via a
        ~1 ms readiness poll (block_until_ready on one head would pin
        the wait to an arbitrary device, the opposite of ready-first;
        a slow device must never stall a sibling whose work finishes
        earlier)."""
        import concurrent.futures as cf

        while True:
            if self._drain_ready():
                return
            heads = [q[0][0] for q in self.in_flight if q]
            if not heads:
                return
            futs = [h for h in heads
                    if hasattr(h, "done") and not h.done()]
            if futs:
                # a finite timeout keeps the already-resolved heads'
                # device programs polled while we wait on the workers
                cf.wait(futs, return_when=cf.FIRST_COMPLETED,
                        timeout=0.05)
            else:
                time.sleep(0.001)

    def _pick_device(self):
        """Next round-robin device with in-flight room, or None when
        every queue is full."""
        ndev = len(self.devices)
        for k in range(ndev):
            idev = (self._rr + k) % ndev
            if len(self.in_flight[idev]) < self.max_inflight:
                self._rr = (idev + 1) % ndev
                return idev
        return None

    def _flush(self, b):
        if len(b) == 0:
            return
        # opportunistic non-blocking drain first: total in-flight
        # capacity is ndev * max_inflight, and without this a short
        # campaign would only emit checkpoints at the end-of-run drain
        self._drain_ready()
        idev = self._pick_device()
        while idev is None:
            self._drain_any()
            idev = self._pick_device()
        tr = self.tracer
        if tr.enabled:
            # bucket identity for the trace, captured BEFORE launch
            # clears the bucket (_bucket_shape; parse_shape_key is its
            # warmup-side inverse)
            shape = _bucket_shape(b)
            n_subints = len(b)
        self._bucket_t0.pop(b.key, None)  # deadline clock resets
        # seq comes from the TRACER, not this executor: several
        # executors may share one trace (stream_ipta_campaign), and
        # the report pairs dispatch/h2d/drain events by seq — assigned
        # BEFORE launch so the copy stage can stamp its h2d events
        seq = tr.next_seq()
        lane = b.lane if b.lane is not None else self.lane
        rec = lane.launch(b, self.pipelines[idev], seq)
        if rec is None:
            return
        self.nfit += 1
        self.devices_used.add(idev)
        self.dispatch_counts[idev] += 1
        for ia, _ in rec[1]:
            if ia in self.undispatched:
                self.undispatched[ia] -= 1
                if self.undispatched[ia] == 0:
                    del self.undispatched[ia]
        q = self.in_flight[idev]
        # the record carries its lane: drains scatter through the lane
        # that launched the bucket (per-request physics in service
        # mode); seq stays at index 3 — the copy-overlap closure in
        # __init__ reads r[3]
        q.append(rec + (seq, lane))
        # the bound is EXACT: _pick_device guaranteed room, so no
        # queue ever holds more than max_inflight dispatches (the old
        # append-then-drain order admitted max_inflight + 1)
        self.peak_inflight = max(self.peak_inflight, len(q))
        if self.on_launch is not None:
            # owner hook (serving loop): owners snapshot + pad rows of
            # this dispatch — the batch-occupancy/coalesce signal
            self.on_launch(seq, rec[1],
                           (-len(rec[1])) % self.nsub_batch)
        if tr.enabled:
            # cold = first dispatch of this bucket shape on this
            # device: the worker will pay the jit trace + XLA compile
            # (jax keys its cache on input placement), so the
            # dispatch -> dispatched gap on cold records is the
            # K-chip cold-start cost pptrace accounts for
            cold = (shape, idev) not in self._warm
            self._warm.add((shape, idev))
            tr.emit("dispatch", seq=seq, device=idev, shape=shape,
                    n=n_subints, queue_depth=len(q), cold=cold)
            tr.counter("dispatches")
            tr.counter(f"dispatches_dev{idev}")
            tr.gauge_max("peak_inflight", len(q))
            handle = rec[0]
            if hasattr(handle, "add_done_callback"):
                # fires on the dispatch worker thread the moment the
                # h2d copy + program enqueue (+ compile, when cold)
                # finish — the tracer is thread-safe by contract
                handle.add_done_callback(
                    lambda f, seq=seq, idev=idev: tr.emit(
                        "dispatched", seq=seq, device=idev))

    @property
    def h2d_bytes(self):
        """Total bytes the copy stages shipped host->device."""
        return sum(pl.h2d_bytes for pl in self.pipelines)

    @property
    def h2d_logical_bytes(self):
        """Total LOGICAL bytes behind those copies — what would have
        shipped without transport compression (equal to h2d_bytes when
        the codec never engaged)."""
        return sum(pl.h2d_logical_bytes for pl in self.pipelines)

    @property
    def codec_duration(self):
        """Total seconds the copy stages spent probing/encoding the
        transport codec."""
        return sum(pl.codec_s for pl in self.pipelines)

    @property
    def h2d_duration(self):
        """Total seconds the copy stages spent moving bytes."""
        return sum(pl.h2d_s for pl in self.pipelines)

    @property
    def h2d_overlap_duration(self):
        """Seconds of copy time that ran while a fit was in flight on
        the same device (the link hidden behind compute)."""
        return sum(pl.h2d_overlap_s for pl in self.pipelines)

    def _shutdown(self, wait):
        for pl in self.pipelines:
            pl.shutdown(wait)

    def admit(self, iarch, datafile, d, ok, lane=None):
        """Prepare one loaded archive through ``lane`` (default: the
        executor's own) and fill its subints into the shared buckets,
        flushing any bucket that reaches nsub_batch.  Returns the
        number of per-subint entries admitted, or None when the lane
        skipped the archive (it emitted the typed archive_skip).

        This is the driver-agnostic feeding interface: run() calls it
        per archive of a fixed list; the serving loop calls it with a
        per-request lane, so subints from different requests coalesce
        whenever their bucket keys match."""
        lane = self.lane if lane is None else lane
        tr = self.tracer
        t_prep = time.time()
        prep = lane.prepare(iarch, datafile, d, ok)
        if prep is None:
            # the lane already emitted archive_skip with the real
            # reason (it shares this executor's tracer)
            tr.counter("archives_skipped")
            return None
        m, per_subint = prep
        if not self.service:
            # run()-only growing state: the finalize() meta order and
            # the in-order checkpoint ledger (a serving owner keeps its
            # own per-request order and calls forget() instead)
            self.meta.append(m)
            self._ckpt_order.append(iarch)
            self._prep_idx[iarch] = len(self._ckpt_order) - 1
        self.meta_by_iarch[iarch] = m
        self._lane_by_iarch[iarch] = lane
        self.remaining[iarch] = len(ok)
        self.undispatched[iarch] = len(per_subint)
        if tr.enabled:
            tr.emit("archive_prepare", iarch=iarch,
                    datafile=datafile, n_ok=len(ok),
                    n_subints=len(per_subint),
                    prep_s=round(time.time() - t_prep, 6))
            tr.counter("archives_prepared")
        for key, factory, fill in per_subint:
            b = self.buckets.get(key)
            if b is None:
                b = self.buckets[key] = factory()
                b.key = key
                b.lane = lane
            fill(b)
            if key not in self._bucket_t0 and len(b):
                # deadline clock: when the bucket's OLDEST pending
                # subint arrived (flush_stale's continuous-batching
                # trigger); reset on every flush
                self._bucket_t0[key] = time.monotonic()
            if len(b) >= self.nsub_batch:
                self._flush(b)
        return len(per_subint)

    def flush_all(self):
        """Launch every non-empty bucket (end of stream, staleness
        horizon, or a serving drain)."""
        for b in self.buckets.values():
            if len(b):
                self._flush(b)

    def flush_stale(self, max_age_s):
        """Continuous-batching deadline policy: launch each partially-
        filled bucket whose OLDEST pending subint has waited at least
        ``max_age_s`` — a bucket dispatches when full OR when its head
        request has waited long enough, so light traffic still meets
        latency targets while heavy traffic fills buckets completely.
        Returns the number of buckets flushed."""
        if not self._bucket_t0:
            return 0
        now = time.monotonic()
        n = 0
        for key, t0 in list(self._bucket_t0.items()):
            if now - t0 < max_age_s:
                continue
            b = self.buckets.get(key)
            if b is not None and len(b):
                self._flush(b)
                n += 1
            else:
                self._bucket_t0.pop(key, None)
        return n

    def oldest_bucket_age(self):
        """Seconds the oldest pending (unfilled) bucket entry has
        waited, or None when no bucket holds work — what a serving
        loop sleeps against between deadline flushes."""
        if not self._bucket_t0:
            return None
        return time.monotonic() - min(self._bucket_t0.values())

    def drain_all(self):
        """Block until every in-flight dispatch has drained."""
        while any(self.in_flight):
            self._drain_any()

    def assemble_leftover(self, iarch):
        """Assemble an archive that never completed through the drain
        (e.g. a lane admitting fewer bucket entries than ok subints);
        idempotent."""
        if iarch in self.assembled:
            return self.assembled[iarch]
        m = self.meta_by_iarch[iarch]
        out = self._lane_by_iarch.get(iarch, self.lane).assemble(
            m, self.results)
        self.assembled[iarch] = out
        if self.tracer.enabled:
            self.tracer.emit("archive_done", iarch=iarch,
                             datafile=m.datafile)
        if self.on_archive_done is not None:
            self.on_archive_done(iarch, m, out)
        return out

    def forget(self, iarch):
        """Drop one archive's bookkeeping after its owner consumed the
        assembly — what keeps a LONG-LIVED (service=True) executor's
        memory O(live requests) instead of O(requests ever served)."""
        self.meta_by_iarch.pop(iarch, None)
        self.assembled.pop(iarch, None)
        self.remaining.pop(iarch, None)
        self._lane_by_iarch.pop(iarch, None)
        self.undispatched.pop(iarch, None)
        self._prep_idx.pop(iarch, None)

    def finalize(self):
        """Late assemblies (anything not completed through the drain,
        e.g. archives whose subints all failed) in archive order, then
        the final in-order checkpoint flush."""
        for m in self.meta:
            self.assemble_leftover(m.iarch)
        self._ckpt_flush()

    def run(self):
        # a failed dispatch/assembly must not leave ANY worker thread
        # grinding through queued h2d copies (each holding a full
        # stacked batch) while the exception propagates
        try:
            tr = self.tracer
            for iarch, (datafile, d) in enumerate(
                    _iter_archives(self.datafiles, self.loader,
                                   self.prefetch)):
                if isinstance(d, Exception):
                    tr.emit("archive_skip", datafile=datafile,
                            reason=str(d))
                    tr.counter("archives_skipped")
                    log(f"Skipping {datafile}: {d}", level="warn",
                        tracer=None)
                    continue
                ok = np.asarray(d.ok_isubs, int)
                if d.nsub == 0 or len(ok) == 0:
                    tr.emit("archive_skip", datafile=datafile,
                            reason="no subints to fit")
                    tr.counter("archives_skipped")
                    log(f"No subints to fit in {datafile}; skipping.",
                        level="warn", tracer=None)
                    continue
                if self.admit(iarch, datafile, d, ok) is None:
                    continue
                # checkpoint-staleness horizon: an early archive whose
                # rare-shape bucket never fills would hold back every
                # later archive's in-order checkpoint write; once it
                # lags CKPT_STALENESS_HORIZON prepared archives,
                # force-flush all pending buckets so completed work
                # keeps reaching disk (see the constant's comment for
                # why this stays deterministic across device counts)
                # lag is counted in PREPARED archives (the unit the
                # horizon promises): skipped/failed archives consume
                # enumerate indices but defer nothing, so raw iarch
                # deltas would fire the flush early on resume runs
                head_d = min(self.undispatched, default=None)
                if head_d is not None and \
                        self._prep_idx[iarch] - self._prep_idx[head_d] \
                        >= CKPT_STALENESS_HORIZON:
                    if tr.enabled:
                        tr.emit(
                            "force_flush",
                            datafile=self.meta_by_iarch[head_d].datafile,
                            lag=self._prep_idx[iarch]
                            - self._prep_idx[head_d])
                        tr.counter("force_flushes")
                    self.flush_all()
            self.flush_all()
            self.drain_all()
        except BaseException:
            self._shutdown(wait=False)
            raise
        self._shutdown(wait=True)
        self.finalize()
        return self.meta, self.assembled


def _load_raw(f):
    """Raw streaming load: undecoded DATA samples + the small per-
    archive metadata TOA assembly needs.

    Sample types: int16, unsigned/signed byte, float32, or sub-byte
    NBIT=1/2/4 packed DATA columns (ops/decode RAW_CODES; packed
    payloads ship their PACKED bytes — codes 'p1'/'p2'/'p4' — and the
    fused program unpacks the bit planes on device: a 2-bit archive
    ships 32x fewer bytes than the decoded-f64 fallback).  General
    FITS column TSCAL/TZERO scaling ships as two scalars the device
    decode folds in before DAT_SCL/DAT_OFFS.
    read_archive(decode=False) refuses the remaining unrepresentable
    layouts (misaligned sub-byte pol planes, packed + FITS-scaled, or
    the PPT_RAW_SUBBYTE escape hatch) and the caller falls back to
    the decoded lane.  Polarization is universal: npol
    == 1 ships as-is; an IQUV state ships only its Stokes-I plane
    (pol 0 — a host INDEX into the undecoded payload, no extra bytes;
    for packed payloads the pol planes are byte-aligned by the reader,
    so the slice stays an index);
    any other multi-pol state (AA+BB, Coherence) ships its TWO summand
    pols and the device decode baselines each pol then sums — the same
    remove_baseline-then-pscrunch order as load_data, so the lanes
    stay digit-identical.  Dedispersed-on-disk archives are supported:
    the device program re-disperses them (matmul-DFT rotation by the
    stored DM) before fitting, mirroring load_data's dededisperse-on-
    load."""
    arch = read_archive(f, decode=False)
    if arch.raw_code in ("p1", "p2", "p4") \
            and bucket_pad_to(arch.nchan) != arch.nchan:
        # bucket-lattice coarsening pads CHANNELS, which has no
        # byte-aligned meaning inside a packed bit stream — decoded
        # fallback (loud, so pptrace's skip ledger names it)
        raise ValueError(
            f"{f}: sub-byte raw payloads cannot channel-pad "
            f"(config.bucket_pad); decoding on host instead")
    if arch.npol == 1 or arch.get_state() == "Stokes":
        # Stokes I is pol 0: index the wire payload, ship one pol
        raw = arch.raw_data[:, 0]
        scl = arch.raw_scl[:, 0]
        offs = arch.raw_offs[:, 0]
        pol_sum = False
    else:
        # AA+BB / Coherence: I = pol0 + pol1, decoded and baselined
        # per pol ON DEVICE (twice the payload bytes of one pol, but
        # still <= decoded float32 — and the host never decodes)
        raw = arch.raw_data[:, :2]
        scl = arch.raw_scl[:, :2]
        offs = arch.raw_offs[:, :2]
        pol_sum = True
    weights = arch.get_weights()
    weights_norm = np.where(weights == 0.0, 0.0, 1.0)
    nsub = arch.nsub
    ok_isubs = np.compress(weights_norm.mean(axis=1),
                           np.arange(nsub)).astype(int)
    from ..io.telescopes import telescope_code

    return DataBunch(
        raw_mode=True, raw=raw, scl=scl, offs=offs,
        raw_code=arch.raw_code, pol_sum=pol_sum,
        tscal=arch.raw_tscal, tzero=arch.raw_tzero,
        weights=weights, ok_isubs=ok_isubs,
        nsub=nsub, nchan=arch.nchan, nbin=arch.nbin,
        freqs=arch.freqs_table, Ps=arch.folding_periods(),
        epochs=arch.epochs(), subtimes=list(arch.tsubints),
        doppler_factors=arch.doppler_factors(),
        DM=arch.get_dispersion_measure(),
        dmc=bool(arch.get_dedispersed()),
        dedisp_nu=arch.dedispersion_ref_freq(),
        nu0=arch.get_centre_frequency(), bw=arch.get_bandwidth(),
        backend=arch.get_backend_name(),
        frontend=arch.get_receiver_name(),
        backend_delay=arch.get_backend_delay(),
        telescope=arch.get_telescope(),
        telescope_code=telescope_code(arch.get_telescope()))


def _raw_decode(raw, scl, offs, nbin, ft, redisp=False,
                redisp_turns=None, dft_fold=None, code="i16",
                pol_sum=False, tscal=None, tzero=None, pack_w=None,
                vmin=None):
    """Stage 1 of the fused raw-bucket program: the transport-codec
    unpack when the copy stage shipped a width-reduced payload
    (``pack_w``/``vmin`` — io/blockcodec; the inverse is the same
    bit-plane op the sub-byte NBIT lane uses), sample decode (scl/offs
    affine per the wire sample type — ops/decode.decode_stokes_I,
    which also unpacks sub-byte packed codes and folds in general
    column TSCAL/TZERO), min-window baseline subtraction, the Stokes-I
    pol reduction for two-pol payloads, and (for dedispersed-on-disk
    archives) the on-device re-dispersion rotation.  Split out of
    _raw_fit_fn so the stage-attribution profiler (benchmarks/
    attrib.py) times prefixes of the REAL program — this is the single
    source of truth for the decode stage."""
    from ..ops.decode import decode_stokes_I, unpack_bitplanes

    if pack_w is not None:
        # transport codec: (nb, nbytes) packed residuals + per-subint
        # minima -> the original integer sample values, exactly (every
        # integer here is far below 2**24, exact in f32)
        nchan = scl.shape[-1]
        nsamp = (2 if pol_sum else 1) * nchan * nbin
        v = raw if pack_w == 8 else unpack_bitplanes(raw, pack_w, nsamp)
        shape = raw.shape[:1] + ((2, nchan, nbin) if pol_sum
                                 else (nchan, nbin))
        raw = v.reshape(shape).astype(ft) \
            + jnp.reshape(vmin.astype(ft),
                          (-1,) + (1,) * (len(shape) - 1))
    x = decode_stokes_I(raw, scl, offs, ft, code=code, pol_sum=pol_sum,
                        nbin=nbin, tscal=tscal, tzero=tzero)
    if redisp:
        # dedispersed-on-disk archives: restore the dispersion
        # delays of the stored DM (load_data's dededisperse, here
        # as a matmul-DFT phasor rotation on device).  The turns
        # arrive from host pre-wrapped mod 1 in f64 — raw delays
        # reach hundreds of turns, beyond f32.  Convention matches
        # io/psrfits.rotate_phase(amps, -delays) (psrfits.py:377):
        # phasor exp(-2 i pi k delays).
        from ..ops.fourier import irfft_mm, rfft_mm

        k = jnp.arange(nbin // 2 + 1, dtype=ft)
        ang = -2.0 * jnp.pi * redisp_turns.astype(ft)[..., None] * k
        c, s = jnp.cos(ang), jnp.sin(ang)
        Xr, Xi = rfft_mm(x, fold=dft_fold)
        x = irfft_mm(Xr * c - Xi * s, Xr * s + Xi * c, nbin)
    return x


def _raw_stats(x, cmask, freqs, ft, tiny, noise=None):
    """Stage 2 of the fused raw-bucket program: power-spectrum noise,
    equivalent-width S/N (sort-free exact median — see
    ops.noise.exact_median_lastaxis; the XLA-sort median used to be the
    single most expensive stage of the whole bucket), and the
    S/N-weighted nu_fit seed.  Returns (noise, snr, nu_fit).

    ``noise`` pre-computed lets the inline-zap lane reuse the noise it
    cut on while the S/N and nu_fit derive from the POST-zap mask —
    exactly what fitting an offline-zapped archive computes."""
    if noise is None:
        noise = jnp.maximum(get_noise_PS(x), tiny)
    snr = get_SNR(x, noise) * cmask
    # S/N * nu^-2-weighted center-of-mass frequency (host mirror:
    # pipeline.toas.snr_weighted_nu_fit; reference pplib.py:2715)
    w_nf = jnp.maximum(snr, 0.0) * freqs[None, :] ** -2.0
    den = jnp.sum(w_nf * freqs[None, :] ** -2.0, axis=1)
    nu_fit = jnp.sqrt(jnp.sum(w_nf, axis=1)
                      / jnp.where(den > 0, den, 1.0))
    nu_fit = jnp.where(jnp.isfinite(nu_fit) & (nu_fit > 0),
                       nu_fit, jnp.mean(freqs)).astype(ft)
    return noise, snr, nu_fit


def _postfit_bad_mask(x, r, noise, cmask, modelx, freqs, Ps, dfs, bary,
                      fit_DM, nbin):
    """In-stream twin of toas.GetTOAs.get_channels_to_zap's per-subint
    loop (reference pptoas.py:1266-1343), traceable: rotate the model
    onto the dispersed data at the fitted (phi, DM), scale per channel,
    form the per-channel reduced chi2, and run the iterative
    median-based cut (quality.postfit.postfit_cut_mask — bit-identical
    to the host oracle).  Returns (nb, nchan) bool bad-channel mask.

    The DM the offline pass rotates by is self.DMs — the
    DOPPLER-CORRECTED value (DM_fit * df when barycentered and the RUN
    fit_DM flag is set) — divided back by df inside the rotation call.
    The multiply-then-divide is kept literally (not simplified to
    DM_fit) so the rotation phasor matches the offline lane bit for
    bit.  fit_DM here is the RUN-level flag: a flag-demoted bucket
    still gets the run-level correction offline."""
    from ..ops.rotation import rotate_portrait
    from ..quality.postfit import postfit_cut_mask

    ft = x.dtype
    df = dfs.astype(ft) if bary else jnp.ones_like(Ps)
    DM_corr = r.DM * df if (bary and fit_DM) else r.DM
    aligned = jax.vmap(
        lambda ph, dm, P, nr: rotate_portrait(modelx, -ph, -dm, P,
                                              freqs, nr))(
        r.phi, DM_corr / df, Ps, r.nu_DM)
    nz = jnp.where(noise > 0, noise, jnp.ones_like(noise))
    resid = x - r.scales[..., None] * aligned
    chan_rchi2 = (jnp.sum(resid**2, axis=-1) / nz**2
                  / max(nbin - 1, 1))
    return postfit_cut_mask(chan_rchi2, r.channel_snrs, r.snr,
                            cmask > 0)


def _raw_fit_fn(nchan, nbin, flags, max_iter, log10_tau, tau_mode,
                use_fast, ftname, x_bf16, redisp=False,
                want_flux=False, use_ir=False, compensated=False,
                nharm_eff=None, seed_derotate=True, raw_code="i16",
                pol_sum=False, zap_nstd=None, col_scaled=False,
                pack_w=None, postfit=None):
    """Cache-key normalizing front for _raw_fit_fn_cached: dead knob
    combinations collapse onto one compiled program — compensated is
    meaningless without the scatter engine, and under compensated mode
    the bf16 cross-spectrum knob is dead (fast_scatter_fit_one forces
    f32 X; fit.portrait.effective_x_bf16) — so flipping either under
    the other never recompiles a bit-identical bucket program.

    seed_derotate=False asserts every DM guess in the bucket is zero
    (the launcher checks the host-side DM_guess list): the CCF seed's
    derotation phasor is then the identity and the trig pass over the
    cross-spectrum is skipped — same packed output to the bit, one
    fewer moment-sized pass per subint."""
    from ..fit.portrait import resolve_fit_fused
    from ..ops.decode import PACKED_BITS
    from ..ops.fourier import use_dft_fold

    scat_engine = (flags[3] or flags[4] or log10_tau
                   or tau_mode != "none" or use_ir)
    if not scat_engine:
        compensated = False
    if compensated:
        x_bf16 = False
    if not use_fast:
        nharm_eff = None  # the complex engine is never band-limited
        seed_derotate = True  # only the fast lanes thread the knob
    # dft_fold and fit_fused resolve HERE and ride the cache key (like
    # x_bf16 / seed_derotate): an in-process config flip must retrace,
    # not silently reuse the other arm's program.  fit_fused is
    # normalized onto False wherever it is a no-op (complex engine, no
    # harmonic window) so a dead knob never keys a second bit-identical
    # program; the resolved token also carries the Pallas-kernel and
    # block-size knobs (fit/portrait.resolve_fit_fused).
    fit_fused = resolve_fit_fused(nharm_eff) if use_fast else False
    # decode-fused (Pallas decode+DFT tile): only the plain sub-byte
    # no-scatter windowed lane qualifies — per-channel byte tiling
    # needs nbin*nbit % 8 == 0, and redisp/pol_sum/transport-packing/
    # column-scaling all need the materialized portrait.  (Packed raw
    # never bucket-channel-pads — _load_raw refuses that combination —
    # so the kernel's channel geometry is exact.)
    nbit = PACKED_BITS.get(raw_code)
    pallas_mode = isinstance(fit_fused, str) \
        and fit_fused.startswith("pallas")
    decode_fused = bool(
        pallas_mode and use_fast and not scat_engine
        and nbit is not None and (nbin * nbit) % 8 == 0
        and not pol_sum and not col_scaled and not redisp
        and pack_w is None and nharm_eff is not None)
    return _raw_fit_fn_cached(
        nchan, nbin, flags, max_iter, log10_tau, tau_mode, use_fast,
        ftname, x_bf16, redisp, want_flux, use_ir, compensated,
        nharm_eff, seed_derotate, use_dft_fold(), raw_code, pol_sum,
        zap_nstd, fit_fused, col_scaled, pack_w, decode_fused, postfit)


@lru_cache(maxsize=None)
def _raw_fit_fn_cached(nchan, nbin, flags, max_iter, log10_tau,
                       tau_mode, use_fast, ftname, x_bf16,
                       redisp=False, want_flux=False, use_ir=False,
                       compensated=False, nharm_eff=None,
                       seed_derotate=True, dft_fold=None,
                       raw_code="i16", pol_sum=False, zap_nstd=None,
                       fit_fused=False, col_scaled=False,
                       pack_w=None, decode_fused=False, postfit=None):
    """ONE jitted program for a raw bucket: sample decode (scl/offs
    affine per raw_code — ops/decode; packed sub-byte codes bit-plane
    unpack first; col_scaled folds the general TSCAL/TZERO scalars in
    as one extra fused multiply-add; pack_w selects the
    transport-codec unpack for a width-reduced payload; pol_sum
    reduces two-pol payloads
    to Stokes I), min-window baseline subtraction, power-spectrum noise, S/N,
    nu_fit seeding, the batched fit, and result packing into a single
    (nfield, nb) array — so a bucket costs one h2d of wire-format
    bytes, one
    dispatch, and one small d2h pull.  The decode and stats stages live
    in _raw_decode/_raw_stats (shared with benchmarks/attrib.py's
    prefix programs).

    tau_mode: 'none' (no scattering anywhere), 'neutral' (half-bin
    seed), 'explicit' ((tau_s, nu, alpha) runtime args), 'auto'
    (device-side estimate_tau_batch).  Any mode but 'none' routes to
    the scatter-shaped engine even for degenerate phi-only lanes
    (their fixed tau seed still scatters the model) — the complex-free
    fast_scatter_fit_one lane on fast backends, the complex engine
    otherwise.

    zap_nstd non-None fuses the INLINE RFI excision (ISSUE 12) into
    the program: the iterative median + nstd cut runs on the freshly
    computed device-resident noise levels (quality.zap_keep_mask — the
    whole iteration inside the compiled while_loop, zero host round
    trips), the flagged channels zero the channel mask BEFORE the S/N,
    nu_fit seed, and fit consume it, and one extra packed row ('nzap')
    reports per-subint cut counts.  Fitting an archive whose weights
    were offline-zapped to the same list is digit-identical — the only
    difference is where the zeros in cmask came from."""
    ft = {"float32": jnp.float32, "float64": jnp.float64}[ftname]
    scat_engine = (flags[3] or flags[4] or log10_tau
                   or tau_mode != "none" or use_ir)
    tiny = float(np.finfo(ftname).tiny)

    def run(raw, scl, offs, cmask, modelx, freqs, Ps, DMg, nu_out,
            tau_s, tau_nu, tau_a, alpha0, redisp_turns, ir_r, ir_i,
            tscal=None, tzero=None, vmin=None, dfs=None):
        x = _raw_decode(raw, scl, offs, nbin, ft, redisp=redisp,
                        redisp_turns=redisp_turns, dft_fold=dft_fold,
                        code=raw_code, pol_sum=pol_sum,
                        tscal=tscal if col_scaled else None,
                        tzero=tzero if col_scaled else None,
                        pack_w=pack_w, vmin=vmin)
        nzap = zap_iter = None
        if zap_nstd is None:
            noise, snr, nu_fit = _raw_stats(x, cmask, freqs, ft, tiny)
        else:
            # inline excision: cut on the device-resident noise, THEN
            # derive S/N + nu_fit from the post-zap mask — the exact
            # order an offline-zapped archive's load produces
            from ..quality.excision import zap_keep_mask

            noise = jnp.maximum(get_noise_PS(x), tiny)
            keep, zap_iter = zap_keep_mask(noise, cmask > 0, zap_nstd)
            pre = jnp.sum(cmask, axis=1)
            cmask = cmask * keep.astype(ft)
            nzap = pre - jnp.sum(cmask, axis=1)
            _, snr, nu_fit = _raw_stats(x, cmask, freqs, ft, tiny,
                                        noise=noise)
        nb = x.shape[0]
        if tau_mode == "none":
            tau0 = jnp.zeros(nb, ft)
        elif tau_mode == "neutral":
            tau0 = jnp.full(nb, 0.5 / nbin, ft)
        elif tau_mode == "explicit":
            tau0 = ((tau_s / Ps) * (nu_fit / tau_nu) ** tau_a).astype(ft)
        else:  # auto
            tau0 = estimate_tau_batch(x, modelx, noise, cmask).astype(ft)
        th3 = jnp.log10(jnp.maximum(tau0, 1e-12)) if log10_tau else tau0
        zeros = jnp.zeros(nb, ft)
        theta0 = jnp.stack(
            [zeros, DMg.astype(ft), zeros, th3,
             jnp.broadcast_to(jnp.asarray(alpha0, ft), (nb,))], axis=1)
        nu_out_arr = jnp.broadcast_to(jnp.asarray(nu_out, ft), (nb,))
        if use_fast and not scat_engine and decode_fused:
            # decode-fused Pallas lane: the fit's prepare re-decodes
            # the packed bytes INSIDE the channel-tile kernel
            # (fit/portrait.fast_fit_one_packed), so the big
            # (nb, nchan, nbin) portrait read the DFT prep used to do
            # comes straight from wire bytes; the stats pass above
            # still decodes once (its reductions fuse, and zap/tau
            # seeding need the time-domain portrait).  Bit-identical
            # to the materialized lane: the in-kernel decode chain is
            # per-channel exact and the gemm tiles are shape-identical.
            from ..fit.portrait import (_fast_batch_packed_fn,
                                        _parse_fit_fused)
            from ..ops.decode import PACKED_BITS

            _, blk = _parse_fit_fused(fit_fused)
            bpc = (nbin * PACKED_BITS[raw_code]) // 8
            fit = _fast_batch_packed_fn(FitFlags(*flags), max_iter,
                                        raw_code, nbin,
                                        seed_derotate=seed_derotate,
                                        x_bf16=x_bf16,
                                        nharm_eff=nharm_eff,
                                        dft_fold=dft_fold,
                                        fused_block=blk)
            r = fit(raw.reshape(raw.shape[0], nchan, bpc), scl, offs,
                    modelx, noise, cmask, freqs, Ps, nu_fit,
                    nu_out_arr, theta0)
        elif use_fast and not scat_engine:
            fit = _fast_batch_fn(FitFlags(*flags), max_iter,
                                 None, None, 0, 0,
                                 seed_derotate=seed_derotate,
                                 x_bf16=x_bf16, nharm_eff=nharm_eff,
                                 dft_fold=dft_fold,
                                 fit_fused=fit_fused)
            r = fit(x, modelx, noise, cmask, freqs, Ps, nu_fit,
                    nu_out_arr, theta0)
        elif use_fast:
            # complex-free scattering lane: the fused analytic
            # _cgh_scatter Newton loop shares the matmul-DFT front end
            # (no complex types in the whole program)
            from functools import partial as _partial

            from ..fit.portrait import fast_scatter_fit_one

            one = _partial(
                fast_scatter_fit_one, fit_flags=FitFlags(*flags),
                log10_tau=log10_tau, max_iter=max_iter,
                compensated=compensated, x_bf16=x_bf16,
                nharm_eff=nharm_eff, seed_derotate=seed_derotate,
                dft_fold=dft_fold, fit_fused=fit_fused)
            r = jax.vmap(one, in_axes=(0, None, 0, 0, None, 0, 0, 0, 0,
                                       None, None))(
                x, modelx, noise, cmask, freqs, Ps, nu_fit,
                nu_out_arr, theta0, ir_r if use_ir else None,
                ir_i if use_ir else None)
        else:
            # ir as complex only INSIDE the program (some tunneled
            # transports cannot move complex buffers at all)
            ir_FT = (jax.lax.complex(ir_r, ir_i) if use_ir else None)
            r = fit_portrait_batch(
                x, modelx, noise, freqs, Ps,
                nu_fit, nu_out=nu_out_arr, theta0=theta0,
                fit_flags=FitFlags(*flags), chan_masks=cmask,
                log10_tau=log10_tau, max_iter=max_iter,
                use_scatter=scat_engine,
                ir_FT=ir_FT)
        fields = [getattr(r, k) for k in _result_keys(flags)]
        if want_flux:
            # flux reduces to 3 scalars per subint ON DEVICE: pulling
            # the (nb, nchan) scales instead would break the
            # one-small-pull design
            fields += list(_flux_rows(r.scales, r.scale_errs,
                                      jnp.mean(modelx, axis=-1),
                                      cmask, freqs))
        if nzap is not None:
            # per-subint inline-zap cut count + in-loop iteration
            # count (two scalar rows — keeps the one-small-pull design
            # while the trace still learns channels-cut-per-archive
            # and proves the iterating happened inside the program)
            fields += [nzap, zap_iter.astype(ft)]
        packed = jnp.stack([jnp.asarray(f).astype(ft) for f in fields])
        if postfit is not None:
            # in-stream post-fit red-chi2/S-N cut (ISSUE 16 satellite):
            # nchan extra packed rows carry the per-channel bad mask —
            # still one small pull (nchan << nbin)
            bary_pf, fit_DM_run = postfit
            bad = _postfit_bad_mask(x, r, noise, cmask, modelx, freqs,
                                    Ps, dfs, bary_pf, fit_DM_run, nbin)
            packed = jnp.concatenate(
                [packed, jnp.swapaxes(bad, 0, 1).astype(ft)], axis=0)
        return packed

    return jax.jit(run)


_RESULT_KEYS = ("phi", "phi_err", "DM", "DM_err", "nu_DM", "snr",
                "chi2", "dof", "nfeval", "return_code")


def _result_keys(flags):
    """Per-subint result fields to pull for a bucket's flag set."""
    keys = _RESULT_KEYS
    if flags[2]:
        # no nu_GM: the stream lane has no nu_refs output and the TOA
        # flags don't carry it (matching get_TOAs' .tim emission), so
        # pulling it would be a dead d2h row per dispatch
        keys = keys + ("GM", "GM_err")
    if flags[3]:
        keys = keys + ("tau", "tau_err", "alpha", "alpha_err", "nu_tau")
    return keys


def _raw_rows(bucket, idx0):
    """Snapshot a raw bucket's padded per-subint rows (cheap list
    gathers on the caller's thread — the bucket is CLEARED right after
    launch, so the pipeline's copy stage must never read it) plus the
    redisp flag, which selects the compiled program and therefore must
    resolve before the copy stage runs."""
    rows = ([bucket.raw[i] for i in idx0],
            [bucket.scl[i] for i in idx0],
            [bucket.offs[i] for i in idx0])
    dedisp = np.asarray([bucket.dedisp[i] for i in idx0])  # (n, 2)
    redisp = bool(np.any(dedisp[:, 0] != 0.0))
    return rows, dedisp, redisp


def _stack_rows(rows, dedisp, redisp, Ps, freqs):
    """Stack snapshotted raw rows into the dispatch payload and
    compute the host-side re-dispersion turns (f64 on host, wrapped to
    [-0.5, 0.5) before the f32 device trig — raw delays reach 100s of
    turns).  Runs on the transfer pipeline's COPY worker, so the
    stacking pass overlaps in-flight fits instead of blocking the
    archive loop."""
    raw = np.stack(rows[0])
    scl = np.stack(rows[1])
    offs = np.stack(rows[2])
    if redisp:
        freqs_h = np.asarray(freqs, np.float64)
        turns = (Dconst * dedisp[:, :1] / Ps[:, None]) * (
            freqs_h[None, :] ** -2.0 - dedisp[:, 1:] ** -2.0)
        turns = (turns + 0.5) % 1.0 - 0.5
    else:
        turns = np.zeros((len(rows[0]), 1))
    return raw, scl, offs, turns


def _stack_raw(bucket, idx0, Ps):
    """Snapshot + stack in one call — the serialized convenience the
    stage-attribution profiler (benchmarks/attrib.py) times; the
    drivers run the two halves on different threads."""
    rows, dedisp, redisp = _raw_rows(bucket, idx0)
    raw, scl, offs, turns = _stack_rows(rows, dedisp, redisp, Ps,
                                        bucket.freqs)
    return raw, scl, offs, redisp, turns


def _on_device(device):
    """Default-device context for a dispatch closure: uncommitted
    intermediates (eager glue in the batch wrappers, complex kernel
    reassembly) must land on the bucket's device too, or mixed
    placements error eagerly.  None = no-op (default device)."""
    return (jax.default_device(device) if device is not None
            else _null_ctx())


class _DevicePipeline:
    """Two-stage host->device dispatch pipeline for ONE device — the
    transfer pipeline that hides the h2d link behind in-flight compute
    (ISSUE 6 tentpole).

    Stage 1, the COPY worker, stacks the bucket payload, converts
    dtypes, and ``device_put``s it — the host->device move that
    dominates campaign wall time on tunneled runtimes.  Stage 2, the
    FIT worker, enqueues the fused program on the copied arrays.  A
    bounded semaphore of ``depth`` buckets gates admission: depth 1
    serializes copy against fit-enqueue (the pre-pipeline single-
    worker behavior, kept as the A/B arm), depth 2 (default,
    ``config.stream_pipeline_depth``) double-buffers so bucket N+1's
    h2d runs while bucket N's fused fit executes.  Output is
    byte-identical for any depth — the pipeline reorders WHEN bytes
    move, never what is computed.

    Telemetry: ``h2d_start`` fires on the copy worker as a bucket's
    move begins (``overlap`` = the device had an undrained dispatch in
    flight, i.e. the link is hidden behind compute) and ``h2d_done``
    carries the byte count and duration — what pptrace's link section
    aggregates into utilization and stall fraction.  The byte/second
    totals also accumulate here for the drivers' run_end summary."""

    def __init__(self, device, idev, depth, tracer, inflight_fn):
        from concurrent.futures import ThreadPoolExecutor

        from ..io.blockcodec import CostModel

        self.device = device
        self.idev = idev
        self.depth = max(1, int(depth))
        self.tracer = tracer
        self._inflight_fn = inflight_fn
        self._sem = threading.BoundedSemaphore(self.depth)
        self.copy_ex = ThreadPoolExecutor(max_workers=1)
        self.fit_ex = ThreadPoolExecutor(max_workers=1)
        self.h2d_bytes = 0
        self.h2d_logical_bytes = 0
        self.h2d_s = 0.0
        self.h2d_overlap_s = 0.0
        self.codec_s = 0.0
        # per-device transport cost model (ISSUE 15): fed the live
        # link rate from this pipeline's own copies; the raw copy
        # closures consult it when config.transport_compress='auto'
        self.cost = CostModel()

    def submit(self, copy_fn, fit_fn, seq):
        """Admit one bucket: ``copy_fn() -> (dev_args, nbytes)`` runs
        on the copy worker, ``fit_fn(*dev_args)`` on the fit worker as
        soon as both the copy and the previous fit-enqueue finish.
        Returns the fit Future.  Blocks the caller only when ``depth``
        buckets already occupy the pipeline — back-pressure that is
        released as fits clear the ENQUEUE stage, which never depends
        on the caller draining results, so no deadlock."""
        self._sem.acquire()
        copy_fut = self.copy_ex.submit(self._run_copy, copy_fn, seq)
        return self.fit_ex.submit(self._run_fit, copy_fut, fit_fn)

    def _run_copy(self, copy_fn, seq):
        tr = self.tracer
        # overlap: an EARLIER dispatch was UNFINISHED (future pending,
        # or its device program still running) on this device while the
        # copy started — the link hid behind compute.  The flag is the
        # h2d-vs-fit overlap signal pptrace's stall fraction reports;
        # already-completed-but-undrained dispatches, this copy's own
        # record, and admitted-but-not-yet-copied successors do NOT
        # count, those would flatter the number.
        overlap = bool(self._inflight_fn(seq))
        if tr.enabled:
            tr.emit("h2d_start", seq=seq, device=self.idev,
                    overlap=overlap)
        t0 = time.perf_counter()
        out = copy_fn()
        dt = time.perf_counter() - t0
        # copy closures return (args, bytes) or, from the codec-aware
        # raw lanes, (args, bytes, extras) with the logical-byte and
        # codec-wall accounting the compression ledger reports
        if len(out) == 3:
            dev_args, nbytes, extras = out
        else:
            dev_args, nbytes = out
            extras = {}
        logical = int(extras.get("bytes_logical", nbytes))
        codec_s = float(extras.get("codec_s", 0.0))
        self.h2d_bytes += nbytes
        self.h2d_logical_bytes += logical
        self.h2d_s += dt
        self.codec_s += codec_s
        if overlap:
            self.h2d_overlap_s += dt
        # the cost model learns THIS link from every copy (shipped
        # bytes over copy wall — conservative: stacking rides in)
        self.cost.observe_link(nbytes, dt)
        # live link-stall accounting for the 'metrics' op (ISSUE 20):
        # process-global counters ToaServer.metrics() folds in, so
        # ppmon shows the stall fraction without a trace on disk
        record_h2d(nbytes, dt, overlap)
        if tr.enabled:
            ev = dict(seq=seq, device=self.idev, bytes=int(nbytes),
                      h2d_s=round(dt, 6), overlap=overlap,
                      bytes_logical=logical,
                      codec_s=round(codec_s, 6))
            if extras.get("codec") is not None:
                # the cost-model decision ledger: 'engaged' | 'cost'
                # (model declined) | 'ratio' (payload incompressible)
                ev["codec"] = extras["codec"]
            tr.emit("h2d_done", **ev)
        return dev_args

    def _run_fit(self, copy_fut, fit_fn):
        try:
            dev_args = copy_fut.result()
            return fit_fn(*dev_args)
        finally:
            # release on ANY exit (a failed copy included): the
            # semaphore is what un-blocks the submitting thread
            self._sem.release()

    def shutdown(self, wait):
        self.copy_ex.shutdown(wait=wait, cancel_futures=not wait)
        self.fit_ex.shutdown(wait=wait, cancel_futures=not wait)


def _byte_put(device, nbytes):
    """A _dev_put that also counts the bytes it ships: the transfer
    pipeline's copy closures use this so h2d_done telemetry (and the
    drivers' run_end byte accounting) reports the REAL post-conversion
    payload, not an estimate.  ``nbytes`` is a one-element list cell
    the closure accumulates into."""
    def put(a, dtype=None):
        arr = np.asarray(a) if dtype is None else np.asarray(a, dtype)
        nbytes[0] += arr.nbytes
        return jax.device_put(arr, device)
    return put


def _launch(bucket, nu_ref_DM, max_iter, nsub_batch, log10_tau=False,
            tau_mode="none", tau_args=(0.0, 1.0, 0.0), alpha0=0.0,
            pipeline=None, want_flux=False, seq=0, zap_nstd=None,
            postfit=None):
    """Launch ONE fused dispatch for a bucket's pending subints
    through ``pipeline`` (the bucket's _DevicePipeline) and return an
    in-flight record — WITHOUT waiting for the device.  The
    host->device copy (stack + convert + device_put) is SYNCHRONOUS
    and is the campaign bottleneck on tunneled runtimes, so it runs as
    its own pipeline stage on the device's COPY worker, overlapped
    against the FIT worker's program enqueues (double-buffered at
    config.stream_pipeline_depth >= 2) — the caller keeps loading and
    bucketing archives while the bytes move, and the link keeps moving
    bytes while the device fits.  The batch is always padded to a
    multiple of nsub_batch so dispatch shapes stay canonical (each
    distinct shape costs an XLA compile).

    The jitted programs follow their inputs, so one _raw_fit_fn_cached
    entry serves every device of a shape — but jax keys its jit cache
    on input placement, so each device pays its own trace + XLA
    compile on the FIRST dispatch it receives (campaign cold start
    costs ~ndev compiles per bucket shape, measured, not one; see
    config.compile_cache_dir for the cross-process fix); every later
    dispatch is a cache hit."""
    n = len(bucket)
    if n == 0:
        return None
    device = pipeline.device
    pad = (-n) % nsub_batch
    idx0 = list(range(n)) + [0] * pad  # pad with copies of subint 0
    # row SNAPSHOTS on the caller's thread (cheap list gathers — the
    # bucket is cleared below, so the copy stage works from these);
    # the expensive np.stack passes run on the copy worker
    masks_rows = [bucket.masks[i] for i in idx0]
    Ps = np.asarray([bucket.Ps[i] for i in idx0])
    # doppler factors ride only when the in-stream postfit cut needs
    # the doppler-corrected DM for its model rotation
    dfs_h = (np.asarray([bucket.dfs[i] for i in idx0])
             if postfit is not None else None)
    flags = FitFlags(*bucket.flags)
    keys = _result_keys(flags)
    if want_flux:
        keys = keys + ("flux", "flux_err", "flux_ref_freq")
    if zap_nstd is not None and bucket.kind == "raw":
        # the fused inline-zap rows (dec buckets zap at prepare on the
        # host-side masks instead — their noise lives on host anyway)
        keys = keys + ("nzap", "zap_iter")
    nu_out = -1.0 if nu_ref_DM is None else float(nu_ref_DM)
    use_fast = use_fast_fit_default()
    ir_FT = bucket.ir_FT
    modelx, freqs = bucket.modelx, bucket.freqs

    if bucket.kind == "raw":
        rows, dedisp, redisp = _raw_rows(bucket, idx0)
        DMg = np.asarray([bucket.DM_guess[i] for i in idx0])
        col_scaled = bucket.col_scaled
        tscal_h = (np.asarray([bucket.tscal[i] for i in idx0])
                   if col_scaled else None)
        tzero_h = (np.asarray([bucket.tzero[i] for i in idx0])
                   if col_scaled else None)
        ftname = "float32" if use_fast else "float64"
        # bf16/compensated config read per call (cache-key args,
        # mirroring _fast_batch_fn): mid-process toggles take effect
        use_ir = ir_FT is not None
        from ..fit.portrait import use_scatter_compensated

        # per-bucket memoized window (fit.portrait) — only the fast
        # lanes band-limit; the complex engine never does
        hwin = bucket.harmonic_window() if use_fast else None

        def make_fn(pack_w):
            return _raw_fit_fn(
                len(np.asarray(freqs)), bucket.nbin,
                tuple(bool(f) for f in bucket.flags),
                int(max_iter), bool(log10_tau), tau_mode,
                use_fast, ftname,
                use_bf16_cross_spectrum(), redisp=redisp,
                want_flux=want_flux, use_ir=use_ir,
                compensated=use_scatter_compensated(),
                nharm_eff=hwin,
                # all-zero DM guesses make the CCF seed's
                # derotation phasor the identity; the host
                # knows, so the program skips the trig pass
                seed_derotate=bool(np.any(DMg != 0.0)),
                raw_code=bucket.raw_code,
                pol_sum=bucket.pol_sum,
                zap_nstd=zap_nstd, col_scaled=col_scaled,
                pack_w=pack_w, postfit=postfit)

        fn = make_fn(None)
        ft = jnp.float32 if use_fast else jnp.float64
        t_s, t_nu, t_a = tau_args
        # compressed transport (ISSUE 15): the copy worker may ship a
        # width-reduced payload — the decision depends on the stacked
        # payload's dynamic range and the live link/codec rates, both
        # known only on the copy worker, so `sel` carries the choice
        # to the fit stage (which runs strictly AFTER the copy for
        # this bucket: the pipeline's _run_fit waits on copy_fut).
        # Packed sub-byte codes are already minimal and f32 payloads
        # carry no integer residual structure — integers only.
        from ..io.blockcodec import (encode_rows, probe_width,
                                     resolve_transport_compress)

        compress_mode = resolve_transport_compress()
        can_compress = (compress_mode is not False
                        and bucket.raw_code in ("i16", "u8", "i8"))
        sel = {}
        # the response ships as TWO REAL arrays (the complex engine
        # reassembles them device-side inside the program — complex
        # buffers cannot cross some tunneled transports).  A
        # band-limited bucket slices the kernel to the window on the
        # host first.  Split here as HOST numpy so the placement below
        # commits them to the bucket's device like every other input.
        if use_ir:
            ir_src = np.asarray(ir_FT)
            if hwin is not None:
                ir_src = ir_src[..., :hwin]
            ir_r_h, ir_i_h = ir_src.real, ir_src.imag
        else:
            ir_r_h = ir_i_h = None

        def copy():
            raw, scl, offs, turns = _stack_rows(rows, dedisp, redisp,
                                                Ps, freqs)
            masks = np.stack(masks_rows)
            payload, vmin_h, codec_s, decision = raw, None, 0.0, None
            if can_compress:
                t0c = time.perf_counter()
                vmin_w, w = probe_width(raw)
                decision = "ratio"  # no width below the wire dtype
                if w is not None:
                    shipped_est = raw.shape[0] * (
                        (raw.size // raw.shape[0]) * w // 8 + 4)
                    if compress_mode is True or \
                            pipeline.cost.predict(raw.nbytes,
                                                  shipped_est):
                        payload = encode_rows(raw, vmin_w, w)
                        vmin_h = vmin_w
                        sel["pack"] = int(w)
                        decision = "engaged"
                    else:
                        decision = "cost"
                codec_s = time.perf_counter() - t0c
                if "pack" in sel:
                    # learn the real encode rate from full encodes
                    # only (a probe-only pass is ~half the wall and
                    # would flatter the model)
                    pipeline.cost.observe_codec(raw.nbytes, codec_s)
            nbytes = [0]
            put = _byte_put(device, nbytes)
            with _on_device(device):
                # payload (+ its vmin sidecar) first, so the byte
                # counter can split shipped-payload from the shared
                # arguments for the logical-bytes accounting below
                payload_d = put(payload)
                vmin_d = put(vmin_h, ft) if vmin_h is not None else None
                shipped_payload = nbytes[0]
                ir_r = put(ir_r_h, ft) if use_ir else None
                ir_i = put(ir_i_h, ft) if use_ir else None
                tscal_d = put(tscal_h, ft) if col_scaled else None
                tzero_d = put(tzero_h, ft) if col_scaled else None
                args = (payload_d, put(scl, ft), put(offs, ft),
                        put(masks, ft), put(modelx, ft),
                        put(freqs, ft), put(Ps, ft), put(DMg, ft),
                        put(turns, ft), ir_r, ir_i, tscal_d, tzero_d,
                        vmin_d,
                        put(dfs_h, ft) if dfs_h is not None else None)
            # logical bytes: what the dispatch would have shipped
            # uncompressed — only the payload (and its vmin sidecar)
            # differ between the lanes
            logical = nbytes[0] - shipped_payload + raw.nbytes
            return args, nbytes[0], {"bytes_logical": int(logical),
                                     "codec_s": codec_s,
                                     "codec": decision}

        def fit(raw_d, scl_d, offs_d, masks_d, modelx_d, freqs_d,
                Ps_d, DMg_d, turns_d, ir_r, ir_i, tscal_d, tzero_d,
                vmin_d, dfs_d=None):
            # the copy stage has resolved by now; a compressed payload
            # selects the width-keyed program (lru-cached like every
            # other variant)
            fn_use = make_fn(sel["pack"]) if "pack" in sel else fn
            with _on_device(device):
                return fn_use(raw_d, scl_d, offs_d, masks_d, modelx_d,
                              freqs_d, Ps_d, DMg_d, ft(nu_out),
                              ft(t_s), ft(t_nu), ft(t_a), ft(alpha0),
                              turns_d, ir_r, ir_i, tscal_d, tzero_d,
                              vmin_d, dfs_d)
    else:
        ports_rows = [bucket.ports[i] for i in idx0]
        noise_rows = [bucket.noise[i] for i in idx0]
        nu_fit = np.asarray([bucket.nu_fits[i] for i in idx0])
        theta0 = np.stack([bucket.theta0[i] for i in idx0])
        # scattering (fitted, or a fixed nonzero/log10 tau seed in a
        # degenerate lane of a scattering run, or an IR kernel) routes
        # to the scatter-shaped engine — complex-free on fast backends
        scat = (flags[3] or flags[4] or log10_tau
                or bool(np.any(theta0[:, 3] != 0.0))
                or ir_FT is not None)
        hwin = bucket.harmonic_window() if use_fast else None
        dt = jnp.float32 if use_fast else None

        def copy():
            ports = np.stack(ports_rows)
            noise = np.stack(noise_rows)
            masks = np.stack(masks_rows)
            nbytes = [0]
            put = _byte_put(device, nbytes)
            with _on_device(device):
                # placed ONCE per dispatch and shared between the fit
                # call and _flux_rows — a second device_put of
                # modelx/masks/freqs would double their h2d bytes on
                # exactly the link that bottlenecks the campaign
                args = (put(ports, dt), put(modelx, dt),
                        put(noise, dt), put(freqs, dt), put(Ps, dt),
                        put(nu_fit, dt), put(theta0, dt),
                        put(masks, dt))
                if dfs_h is not None:
                    args = args + (put(dfs_h, dt),)
            return args, nbytes[0]

        def fit(ports_d, modelx_d, noise_d, freqs_d, Ps_d, nu_fit_d,
                theta0_d, masks_d, dfs_d=None):
            with _on_device(device):
                if use_fast:
                    # both regimes share the complex-free matmul-DFT
                    # lane; scattering buckets route to the fused
                    # analytic _cgh_scatter Newton loop inside
                    r = fit_portrait_batch_fast(
                        ports_d, modelx_d, noise_d, freqs_d, Ps_d,
                        nu_fit_d, nu_out=nu_ref_DM, theta0=theta0_d,
                        fit_flags=flags, chan_masks=masks_d,
                        max_iter=max_iter, log10_tau=log10_tau,
                        ir_FT=ir_FT, use_scatter=scat,
                        harmonic_window=hwin if hwin is not None
                        else False)
                else:
                    r = fit_portrait_batch(
                        ports_d, modelx_d, noise_d, freqs_d, Ps_d,
                        nu_fit_d, nu_out=nu_ref_DM, theta0=theta0_d,
                        fit_flags=flags, chan_masks=masks_d,
                        log10_tau=log10_tau, max_iter=max_iter,
                        ir_FT=ir_FT)
                # pack into one array so draining costs a single d2h
                # pull (~100 ms round-trip each on tunneled runtimes);
                # flux reduces to 3 per-subint rows on device
                # (_flux_rows)
                fields = [jnp.asarray(getattr(r, k)).astype(
                    r.phi.dtype) for k in _result_keys(flags)]
                if want_flux:
                    fields += [f.astype(r.phi.dtype)
                               for f in _flux_rows(
                        r.scales, r.scale_errs,
                        jnp.mean(modelx_d, axis=-1),
                        masks_d, freqs_d)]
                packed = jnp.stack(fields)
                if postfit is not None:
                    # in-stream postfit cut: nchan extra rows with the
                    # per-channel bad mask (see _raw_fit_fn_cached)
                    bad = _postfit_bad_mask(
                        ports_d, r, noise_d, masks_d, modelx_d,
                        freqs_d, Ps_d, dfs_d, postfit[0], postfit[1],
                        int(ports_d.shape[-1]))
                    packed = jnp.concatenate(
                        [packed, jnp.swapaxes(bad, 0, 1).astype(
                            packed.dtype)], axis=0)
                return packed

    handle = pipeline.submit(copy, fit, seq)
    rec = (handle, list(bucket.owners), keys)
    bucket.clear()
    return rec


def _flux_rows(scales, scale_errs, means, cmask, freqs):
    """(flux, flux_err, flux_ref_freq) per subint, on device — the
    streaming twin of the per-subint flux estimate (reference
    pptoas.py:595-624, mirrored in pipeline/toas.py:594-621); parity
    guarded by tests/test_stream.py::test_stream_flux_matches_gettoas.

    The scattered-model branch of the reference is omitted on purpose:
    the one-sided-exponential kernel has unit DC gain (B_0 = 1), so the
    model CHANNEL MEANS — the only model quantity flux uses — are
    unchanged by any fitted tau.

    scales/scale_errs: (nb, nchan); means: (nchan,) model channel
    means; cmask: (nb, nchan) 0/1; freqs: (nchan,)."""
    fx = means[None, :] * scales
    fe = jnp.abs(means)[None, :] * scale_errs
    good = (fe > 0.0) & (cmask > 0.0)
    w = jnp.where(good, 1.0 / jnp.where(good, fe, 1.0) ** 2.0, 0.0)
    wsum = w.sum(axis=1)
    ok = wsum > 0.0
    wsafe = jnp.where(ok, wsum, 1.0)
    nmask = jnp.maximum(cmask.sum(axis=1), 1.0)
    # weighted_mean semantics (pipeline/toas.py:40-50): plain mean and
    # infinite error when no positive-error channel exists
    flux = jnp.where(ok, (fx * w).sum(axis=1) / wsafe,
                     (fx * cmask).sum(axis=1) / nmask)
    flux_err = jnp.where(ok, wsafe ** -0.5, jnp.inf)
    ffreq = jnp.where(ok, (freqs[None, :] * w).sum(axis=1) / wsafe,
                      (freqs[None, :] * cmask).sum(axis=1) / nmask)
    return flux, flux_err, ffreq


def _assemble_archive(m, results, modelfile, fit_DM, bary,
                      addtnl_toa_flags, log10_tau=False,
                      alpha_fitted=False, nu_ref_tau=None,
                      fit_GM=False, print_flux=False,
                      print_phase=False, quiet=False,
                      quality_flags=False):
    """Build the TOA objects + DeltaDM stats for one archive from the
    scattered fit results."""
    toas, dDMs, dDM_errs = [], [], []
    for j, isub in enumerate(m.ok):
        r = results.get((m.iarch, int(isub)))
        if r is None:
            continue
        P = m.Ps[j]
        phi = float(r["phi"])
        toa_mjd = m.epochs[j].add_seconds(phi * P + m.backend_delay)
        df = m.dfs[j] if bary else 1.0
        # flag emission follows the RUN's fit_GM like get_TOAs (a
        # degenerate-geometry subint whose GM was dropped still
        # reports gm 0.0, pptoas.py:629-631)
        DM_j, GM_j = doppler_corrected_DM_GM(
            float(r["DM"]), float(r.get("GM", 0.0)), df,
            fit_DM, "GM" in r, bary)
        flags = {}
        if fit_GM:
            flags["gm"] = GM_j
            flags["gm_err"] = float(r.get("GM_err", 0.0))
        if "tau" in r:
            # same flag assembly as GetTOAs (pipeline/toas.py
            # scattering_toa_flags), incl. the -nu_tau re-reference
            flags.update(scattering_toa_flags(
                float(r["tau"]), float(r["tau_err"]),
                float(r["nu_tau"]), float(r["alpha"]),
                float(r.get("alpha_err", 0.0)), P, df, log10_tau,
                alpha_fitted, nu_ref_tau=nu_ref_tau))
        flags.update({
            "be": m.backend, "fe": m.frontend,
            "f": f"{m.frontend}_{m.backend}",
            "nbin": int(m.nbin), "nch": int(m.nchan),
            "subint": int(isub), "tobs": m.subtimes[j],
            "tmplt": str(modelfile), "snr": float(r["snr"]),
            "gof": float(r["chi2"] / max(float(r["dof"]), 1.0)),
        })
        # bf16 guard rail: the packed result carries only the total
        # S/N, so estimate per-channel as snr/sqrt(nchan) (an
        # underestimate — never a false warning)
        from ..fit.portrait import warn_bf16_high_snr
        warn_bf16_high_snr(float(r["snr"]) / max(m.nchan, 1) ** 0.5,
                           quiet=quiet)
        if print_phase:
            flags["phs"] = phi
            flags["phs_err"] = float(r["phi_err"])
        if print_flux:
            flags["flux"] = float(r["flux"])
            flags["flux_err"] = float(r["flux_err"])
            flags["flux_ref_freq"] = float(r["flux_ref_freq"])
        if quality_flags:
            # per-TOA fit diagnostics from the packed result (-snr is
            # always present above); OFF by default so .tim output
            # stays byte-identical to previous releases
            flags["nfev"] = int(r["nfeval"])
            flags["chi2"] = float(r["chi2"])
        flags.update(addtnl_toa_flags)
        DM_out = DM_j if fit_DM else None
        DM_err_out = float(r["DM_err"]) if fit_DM else None
        toas.append(TOA(
            m.datafile, float(r["nu_DM"]), toa_mjd,
            float(r["phi_err"]) * P * 1e6, m.telescope,
            m.telescope_code, DM_out, DM_err_out, flags))
        if fit_DM:
            dDMs.append(DM_j - m.DM0_arch)
            dDM_errs.append(DM_err_out)
    mean, err = delta_dm_stats(dDMs, dDM_errs)
    return toas, mean, err


def _collect_wideband(meta, assembled):
    """Collect TOAs + per-archive DeltaDM statistics in archive order
    from a run's (meta, assembled) — shared by the one-shot driver and
    the serving loop's per-request demux (serve/server.py), so the two
    paths cannot drift on result assembly."""
    TOA_list = []
    order, DM0s, means, errs = [], [], [], []
    for m in meta:
        toas, mean, err = assembled[m.iarch]
        TOA_list.extend(toas)
        order.append(m.datafile)
        DM0s.append(m.DM0_arch)
        means.append(mean)
        errs.append(err)
    return TOA_list, order, DM0s, means, errs


def make_wideband_lane(modelfile, nsub_batch=256, fit_DM=True,
                       fit_GM=False, nu_ref_DM=None, nu_ref_tau=None,
                       DM0=None, bary=True, tscrunch=False,
                       fit_scat=False, log10_tau=True, scat_guess=None,
                       fix_alpha=False, max_iter=25, print_flux=False,
                       print_phase=False,
                       instrumental_response_dict=None,
                       addtnl_toa_flags={}, quiet=False,
                       quality_flags=False, tracer=None,
                       key_prefix=(), zap_inline=False, zap_nstd=None,
                       zap_channels=None, postfit_cut=False):
    """Build the wideband physics lane + archive loader for a template
    and option set — the per-driver half of the streaming split.
    Returns ``(lane, loader)``: the lane supplies _StreamExecutor's
    prepare/launch/scatter/assemble hooks, the loader is what
    _iter_archives (or a serving loop) reads archives with.

    This is the enabling refactor behind the serving subsystem
    (ISSUE 8 / ROADMAP item 2): the executor is driver-agnostic and a
    lane is a VALUE, so a long-lived server builds one lane per
    (template, options) pair, caches it (the TemplateModel load
    amortizes across requests), and feeds every lane into ONE warm
    executor.  ``key_prefix`` namespaces the lane's bucket keys so
    different templates with identical layouts can never share a fused
    dispatch; requests with the SAME template and options reuse the
    same prefix and therefore coalesce.  The one-shot
    stream_wideband_TOAs driver is now a thin client of this factory.

    Option semantics follow stream_wideband_TOAs (which documents
    them, including ``zap_inline``/``zap_nstd``/``zap_channels``);
    ``tracer`` is the telemetry sink prepare's typed archive_skip
    events go to."""
    from .toas import DEFAULT_IR_DICT, build_instrumental_response_FT
    from .zap import resolve_zap_device, resolve_zap_nstd

    tracer = NULL_TRACER if tracer is None else tracer
    # inline excision (ISSUE 12): raw buckets fuse the cut into the
    # device program (zap_nstd_run rides the compiled-program cache
    # key), decoded buckets cut at prepare before any mask-derived
    # quantity; zap_channels feeds PRE-COMPUTED offline lists through
    # lossless in-memory weight zeroing (quality.zap_bunch) — the
    # offline-zap digit-oracle arm
    zap_nstd_run = resolve_zap_nstd(zap_nstd) if zap_inline else None
    # post-fit quality cut (ISSUE 16): the bucket program appends a
    # per-channel bad mask built from model residuals (quality/postfit
    # thresholds) — the tuple carries the two run-level knobs the
    # residual rotation needs (barycentering and whether DM was fit)
    postfit_run = (bool(bary), bool(fit_DM)) if postfit_cut else None
    zap_map = {os.path.abspath(k): v
               for k, v in (zap_channels or {}).items()}
    ird = {**DEFAULT_IR_DICT, **(instrumental_response_dict or {})}
    if len(ird["wids"]) != len(ird["irf_types"]):
        raise ValueError(
            "instrumental_response_dict: wids and irf_types must pair "
            f"up (got {len(ird['wids'])} widths, "
            f"{len(ird['irf_types'])} kinds)")
    use_ir = bool(ird["wids"] or ird["DM-smear"])
    ir_cache = {}  # ir signature -> (nchan, nharm) kernel (one build
    # per distinct layout, not per archive — eager device ops are not
    # free on tunneled runtimes)
    scat_guess = _validate_scat_guess(scat_guess, fit_scat)
    if not fit_scat:
        log10_tau = False
    model = TemplateModel(modelfile, quiet=quiet)
    # scattering baked into the template makes the portrait depend on
    # the folding period (tau seconds -> bins) — such templates must
    # not be shared across archives with different P
    p_dependent = model.has_scattering()

    # f32 load on fast-fit backends: the data feeds the f32 engine
    # anyway, and single precision halves per-archive host time — on
    # CPU (tests/parity) keep f64 so results bit-match GetTOAs
    load_dtype = np.float32 if use_fast_fit_default() else None

    def _loader(f):
        if not tscrunch:
            try:
                # raw lane: undecoded wire samples straight to the
                # accelerator, decode and statistics on device
                return _apply_zap_map(f, _load_raw(f))
            except (ValueError, KeyError):
                pass
        return _apply_zap_map(f, load_for_toas(
            f, tscrunch=tscrunch, quiet=True, dtype=load_dtype))

    def _apply_zap_map(f, d):
        """Offline zap lists applied as in-memory weight zaps at load
        (runs on the prefetch threads; the tracer is thread-safe).
        Bit-identical to loading an archive whose DAT_WTS were zeroed
        — see quality.zap_bunch for why the physical rewrite is not."""
        z = zap_map.get(os.path.abspath(f))
        if z is not None and sum(len(c) for c in z):
            from ..quality.excision import zap_bunch

            zap_bunch(d, z)
            if tracer.enabled:
                tracer.emit("zap_apply", datafile=f,
                            n_channels=sum(len(c) for c in z))
        return d

    # tau seeding mode, resolved once (both lanes)
    default_alpha = (model.gauss.alpha if model.is_gaussian
                     else scattering_alpha)
    if scat_guess is not None and not isinstance(scat_guess, str):
        tau_mode = "explicit"
        tau_args = tuple(float(v) for v in scat_guess)
        alpha0_run = tau_args[2]
    elif fit_scat and scat_guess == "auto":
        tau_mode, tau_args, alpha0_run = "auto", (0.0, 1.0, 0.0), \
            float(default_alpha)
    elif fit_scat:
        tau_mode, tau_args, alpha0_run = "neutral", (0.0, 1.0, 0.0), \
            float(default_alpha)
    else:
        tau_mode, tau_args, alpha0_run = "none", (0.0, 1.0, 0.0), \
            float(default_alpha)

    class _WidebandLane:
        """The wideband physics hooks for _StreamExecutor."""

        def __init__(self):
            # {datafile: {subint: [bad channel indices]}} when
            # postfit_cut is on — the in-stream analogue of
            # GetTOAs.get_channels_to_zap's self.zap_channels
            self.postfit_zaps = {}

        def prepare(self, iarch, datafile, d, ok):
            nchan, nbin = d.nchan, d.nbin
            freqs0 = np.asarray(d.freqs[0], float)
            P_mean = float(np.mean(d.Ps[ok]))
            # bucket-lattice coarsening (config.bucket_pad): pad the
            # DEVICE layout to the next power-of-two channel count
            # with zero-weight channels, so distinct nchans collapse
            # onto one compiled program class.  Host-side statistics
            # and TOA flags keep the true nchan; masked pad channels
            # contribute exactly zero to every fit sum, so output is
            # digit-identical padded vs exact (tests/test_serve.py).
            # Pad frequencies repeat the last channel (extrapolating
            # could cross zero on a descending band, and freqs**-2
            # must stay finite).
            pad_c = bucket_pad_to(nchan) - nchan
            freqs_b = (np.concatenate([freqs0,
                                       np.full(pad_c, freqs0[-1])])
                       if pad_c else freqs0)
            try:
                modelx = model.portrait(freqs_b, nbin, P=P_mean)
            except ValueError as e:
                # typed archive_skip (not just a log line) so pptrace's
                # skipped-archives section shows the REAL mismatch,
                # matching GetTOAs' skip path
                tracer.emit("archive_skip", datafile=datafile,
                            reason=str(e))
                log(f"Skipping {datafile}: {e}", level="warn")
                return None
            base_key = key_prefix + (nchan + pad_c, nbin,
                                     freqs_b.tobytes())
            if p_dependent:
                base_key += (round(P_mean, 12),)

            DM_stored = float(d.DM)
            DM0_arch = DM_stored if DM0 is None else float(DM0)
            DM_guess = DM_stored if DM_stored != 0.0 else DM0_arch

            # instrumental-response FT for this archive's layout (same
            # construction as GetTOAs, pptoas.py:428-434).  DM-smearing
            # makes the kernel archive-specific, so it joins the bucket
            # key; pure achromatic kernels share across same layouts.
            if use_ir:
                ir_sig = ((nchan + pad_c, nbin, freqs_b.tobytes(),
                           tuple(ird["wids"]), tuple(ird["irf_types"]))
                          + ((round(DM_guess, 9), round(P_mean, 12))
                             if ird["DM-smear"] else ()))
                if ir_sig not in ir_cache:
                    ir_cache[ir_sig] = build_instrumental_response_FT(
                        ird, freqs_b, nbin, DM_guess, P_mean,
                        bw=d.get("bw", 0.0))
                ir_FT = ir_cache[ir_sig]
                base_key += (ir_sig[3:],)
            else:
                ir_FT = None
            masks = np.asarray(d.weights[ok] > 0.0, float)
            raw_mode = bool(d.get("raw_mode", False))
            if zap_nstd_run is not None and not raw_mode and len(ok):
                # decoded-lane inline excision: cut BEFORE any
                # mask-derived quantity (nu_fit seed, tau seeds,
                # degenerate-geometry flag demotion), so the result is
                # exactly what an offline-zapped archive's load yields.
                # (Raw buckets cut inside the fused device program —
                # their noise levels never visit the host.)
                from ..quality.excision import (zap_keep_device,
                                                zap_keep_np)

                noise_z = np.asarray(d.noise_stds[ok, 0])
                use_dev = resolve_zap_device(None)
                t0z = time.perf_counter()
                keep, iters = (zap_keep_device if use_dev
                               else zap_keep_np)(noise_z, masks > 0,
                                                 zap_nstd_run)
                wall_z = time.perf_counter() - t0z
                n_cut = int(masks.sum() - (masks * keep).sum())
                masks = masks * keep
                if tracer.enabled:
                    tracer.emit("zap_propose", datafile=datafile,
                                n_channels=n_cut,
                                n_iter=int(np.max(iters, initial=0)),
                                device=bool(use_dev),
                                wall_s=round(wall_z, 6))
                    if n_cut:
                        tracer.emit("zap_apply", datafile=datafile,
                                    n_channels=n_cut)
            masks_b = (np.pad(masks, ((0, 0), (0, pad_c)))
                       if pad_c else masks)

            # keep only what TOA assembly needs — NOT the data cube
            m = DataBunch(
                datafile=datafile, iarch=iarch, ok=ok,
                DM0_arch=DM0_arch, nbin=nbin, nchan=nchan,
                epochs=[d.epochs[isub] for isub in ok],
                Ps=[float(d.Ps[isub]) for isub in ok],
                dfs=[float(d.doppler_factors[isub]) for isub in ok],
                subtimes=[float(d.subtimes[isub]) for isub in ok],
                backend_delay=d.backend_delay, backend=d.backend,
                frontend=d.frontend, telescope=d.telescope,
                telescope_code=d.telescope_code)
            nchx = masks.sum(axis=1).astype(int)

            if not raw_mode:
                ports = np.asarray(d.subints[ok, 0])  # dtype preserved
                noise = np.asarray(d.noise_stds[ok, 0], float)
                snrs_chan = np.asarray(d.SNRs[ok, 0], float) * masks
                nu_fit_arr = snr_weighted_nu_fit(snrs_chan, freqs0)
                # tau/alpha seeds (shared with GetTOAs.get_TOAs) —
                # host seeds from the TRUE layout; only the device
                # payload below is padded
                tau0, alpha0 = scat_seed_tau0(
                    scat_guess, fit_scat, len(ok), nbin, P_mean,
                    nu_fit_arr, default_alpha,
                    ports=ports, modelx=modelx[:nchan], noise=noise,
                    masks=masks)
                if pad_c:
                    # edge-replicated data + noise for the same reason
                    # the raw fill pads with mode="edge": masked-out
                    # channels must carry ORDINARY finite noise so the
                    # fit's weights stay benign
                    ports = np.pad(ports, ((0, 0), (0, pad_c), (0, 0)),
                                   mode="edge")
                    noise = np.pad(noise, ((0, 0), (0, pad_c)),
                                   mode="edge")

            base_flags = (True, bool(fit_DM), bool(fit_GM),
                          bool(fit_scat),
                          bool(fit_scat and not fix_alpha))
            kind = "raw" if raw_mode else "dec"
            # raw payloads bucket by wire sample type, pol reduction,
            # and column-scaling presence too: each combination is its
            # own compiled decode stage, and mixing them would stack
            # incompatible row shapes/dtypes (or drop a scaling)
            raw_code = str(d.get("raw_code") or "i16")
            pol_sum = bool(d.get("pol_sum", False))
            col_scaled = raw_mode and (d.get("tscal") is not None
                                       or d.get("tzero") is not None)
            tscal_val = float(d.get("tscal") or 1.0) if raw_mode else 1.0
            tzero_val = float(d.get("tzero") or 0.0) if raw_mode else 0.0
            per_subint = []
            for j, isub in enumerate(ok):
                # degenerate-geometry demotion — the SAME helper
                # GetTOAs' flag groups use (pipeline/toas.py
                # effective_fit_flags; reference pptoas.py:519-527)
                eff_flags = effective_fit_flags(nchx[j], base_flags)
                key = base_key + (eff_flags, kind)
                if raw_mode:
                    key += (raw_code, pol_sum, col_scaled)

                def factory(freqs_b=freqs_b, nbin=nbin, modelx=modelx,
                            eff_flags=eff_flags, kind=kind,
                            ir_FT=ir_FT, raw_code=raw_code,
                            pol_sum=pol_sum, col_scaled=col_scaled):
                    return _Bucket(freqs_b, nbin, modelx, eff_flags,
                                   kind=kind, ir_FT=ir_FT,
                                   raw_code=raw_code, pol_sum=pol_sum,
                                   col_scaled=col_scaled)

                def fill(b, j=j, isub=int(isub), d=d, masks_b=masks_b,
                         DM_guess=DM_guess, raw_mode=raw_mode,
                         iarch=iarch, pad_c=pad_c,
                         col_scaled=col_scaled, tscal_val=tscal_val,
                         tzero_val=tzero_val):
                    if raw_mode:
                        raw_row = d.raw[isub]
                        scl_row = d.scl[isub]
                        offs_row = d.offs[isub]
                        if col_scaled:
                            b.tscal.append(tscal_val)
                            b.tzero.append(tzero_val)
                        if pad_c:
                            # pad channels REPLICATE the edge channel
                            # (samples and scl/offs), not zeros: the
                            # fused program estimates noise from the
                            # data, and a zero channel's tiny-clamped
                            # noise would blow up the fit's 1/noise^2
                            # weights.  A replicated channel has
                            # ordinary finite noise and is suppressed
                            # by its zero mask exactly like a zapped
                            # channel — the path the GetTOAs parity
                            # tests already pin down.
                            raw_row = np.pad(
                                raw_row, [(0, 0)] * (raw_row.ndim - 2)
                                + [(0, pad_c), (0, 0)], mode="edge")
                            scl_row = np.pad(
                                scl_row, [(0, 0)] * (scl_row.ndim - 1)
                                + [(0, pad_c)], mode="edge")
                            offs_row = np.pad(
                                offs_row,
                                [(0, 0)] * (offs_row.ndim - 1)
                                + [(0, pad_c)], mode="edge")
                        b.raw.append(raw_row)
                        b.scl.append(scl_row)
                        b.offs.append(offs_row)
                        b.DM_guess.append(DM_guess)
                        # dedispersed-on-disk: the device program
                        # restores the stored DM's delays before
                        # fitting; reference frequency honors REF_FREQ
                        b.dedisp.append(
                            (float(d.DM) if d.get("dmc") else 0.0,
                             float(d.get("dedisp_nu")
                                   or d.get("nu0", 0.0) or 0.0)))
                    else:
                        th = np.zeros(5)
                        th[1] = DM_guess
                        th[3] = (np.log10(max(tau0[j], 1e-12))
                                 if log10_tau else tau0[j])
                        th[4] = alpha0
                        b.ports.append(ports[j])
                        b.noise.append(noise[j])
                        b.nu_fits.append(float(nu_fit_arr[j]))
                        b.theta0.append(th)
                    b.masks.append(masks_b[j])
                    b.Ps.append(float(d.Ps[isub]))
                    b.dfs.append(float(d.doppler_factors[isub]))
                    b.owners.append((iarch, isub))

                per_subint.append((key, factory, fill))
            return m, per_subint

        def launch(self, b, pipeline, seq):
            return _launch(b, nu_ref_DM, max_iter, nsub_batch,
                           log10_tau=log10_tau, tau_mode=tau_mode,
                           tau_args=tau_args, alpha0=alpha0_run,
                           pipeline=pipeline, want_flux=print_flux,
                           seq=seq, zap_nstd=zap_nstd_run,
                           postfit=postfit_run)

        def scatter(self, out, owners, keys, results):
            packed = np.asarray(out)
            nk = len(keys)
            for i, owner in enumerate(owners):  # pad lanes discarded
                res = {k: packed[j, i] for j, k in enumerate(keys)}
                if packed.shape[0] > nk:
                    # post-fit quality rows (ISSUE 16): per-channel
                    # bad-channel mask appended past the named fields
                    res["postfit_bad"] = packed[nk:, i]
                results[owner] = res

        def assemble(self, m, results):
            if zap_nstd_run is not None and tracer.enabled:
                # fused raw-lane inline zap: the per-subint cut and
                # in-loop iteration counts came back in the packed
                # 'nzap'/'zap_iter' rows (dec archives emitted their
                # events at prepare instead).  wall_s is 0 by design:
                # the cut runs inside the fit dispatch, there is no
                # separate zap wall to charge.
                rows = [results[(m.iarch, int(isub))] for isub in m.ok
                        if isinstance(results.get((m.iarch, int(isub))),
                                      dict)
                        and "nzap" in results[(m.iarch, int(isub))]]
                if rows:
                    nz = sum(int(r["nzap"]) for r in rows)
                    tracer.emit(
                        "zap_propose", datafile=m.datafile,
                        n_channels=int(nz),
                        n_iter=max(int(r["zap_iter"]) for r in rows),
                        device=True, wall_s=0.0)
                    if nz:
                        tracer.emit("zap_apply", datafile=m.datafile,
                                    n_channels=int(nz))
            if postfit_run is not None:
                # post-fit model-based cut (ISSUE 16): the device
                # program appended a per-channel bad mask; collect it
                # into ppzap-style {subint: [channels]} lists.  The
                # TOAs themselves are NOT modified — the lists are the
                # same artifact GetTOAs + get_channels_to_zap produce
                # offline, ready to feed back as ``zap_channels``.
                zaps = {}
                for isub in m.ok:
                    r = results.get((m.iarch, int(isub)))
                    if isinstance(r, dict) and "postfit_bad" in r:
                        zaps[int(isub)] = sorted(
                            int(c) for c in np.flatnonzero(
                                r["postfit_bad"][:m.nchan] > 0))
                self.postfit_zaps[m.datafile] = zaps
                if tracer.enabled:
                    tracer.emit(
                        "zap_propose", datafile=m.datafile,
                        n_channels=sum(len(v) for v in zaps.values()),
                        n_iter=0, device=True, wall_s=0.0)
            return _assemble_archive(
                m, results, modelfile, fit_DM, bary, addtnl_toa_flags,
                log10_tau=log10_tau,
                alpha_fitted=fit_scat and not fix_alpha,
                nu_ref_tau=nu_ref_tau, fit_GM=fit_GM,
                print_flux=print_flux, print_phase=print_phase,
                quiet=quiet, quality_flags=quality_flags)

    return _WidebandLane(), _loader


def stream_wideband_TOAs(datafiles, modelfile, nsub_batch=256,
                         fit_DM=True, fit_GM=False, nu_ref_DM=None,
                         nu_ref_tau=None, DM0=None, bary=True,
                         tscrunch=False, fit_scat=False, log10_tau=True,
                         scat_guess=None, fix_alpha=False, max_iter=25,
                         prefetch=True, max_inflight=None,
                         print_flux=False, print_phase=False,
                         instrumental_response_dict=None,
                         addtnl_toa_flags={}, tim_out=None,
                         quiet=False, resume=False,
                         skip_archives=None, stream_devices=None,
                         telemetry=None, quality_flags=False,
                         pipeline_depth=None, zap_inline=False,
                         zap_nstd=None, zap_channels=None,
                         postfit_cut=False):
    """Measure wideband (phi[, DM[, tau, alpha]]) TOAs for many
    archives with cross-archive batched dispatches.

    zap_inline=True runs the ppzap median algorithm INLINE (ISSUE 12):
    raw buckets fuse the iterative median + ``zap_nstd``*std noise cut
    into the device program (the cut iterates on the device-resident
    noise levels inside one compiled while_loop — no host round-trips)
    and zero the flagged channels' masks before the S/N, nu_fit seed,
    and fit consume them; decoded-lane archives cut at prepare, before
    any mask-derived quantity.  Output is digit-identical to offline-
    zapping the same channel lists first (see ``zap_channels``), with
    two documented edges: a subint that inline zap empties keeps its
    (all-masked) TOA row where an offline-zapped load would drop the
    subint, and a raw-lane subint cut down into degenerate geometry
    (<= 2 usable channels) keeps its pre-zap fit-flag group.  zap_nstd:
    threshold in stds (None = config.zap_nstd / PPT_ZAP_NSTD).

    zap_channels: {archive path: [subint][channel indices]} of
    PRE-COMPUTED zap lists (e.g. from pipeline.zap.get_zap_channels)
    applied as in-memory weight zaps at load — bit-identical to
    loading an archive whose DAT_WTS were zeroed, which a physical
    ppzap --apply rewrite is NOT (the PSRFITS writer re-quantizes
    DATA).  This is the offline zap-then-fit oracle arm the inline
    lane's digit gates compare against.

    postfit_cut=True runs the POST-fit model-based quality cut inside
    the streaming path (ISSUE 16): each bucket program appends
    per-channel bad-channel rows built from the fitted model's
    residual reduced chi2 and the channel S/N (quality/postfit
    thresholds, same recipe as GetTOAs + get_channels_to_zap), and
    the returned DataBunch carries ``postfit_zaps`` — {archive path:
    {subint: [channel indices]}} ready to feed back as
    ``zap_channels`` on a re-run.  TOAs are NOT modified.

    fit_scat/log10_tau/scat_guess/fix_alpha follow GetTOAs.get_TOAs
    (scat_guess may be (tau_s, nu, alpha), "auto" for the data-driven
    seed, or None for the neutral half-bin); nu_ref_tau re-references
    the reported tau to a fixed frequency, as get_TOAs does; scattering
    buckets run the complex engine, no-scattering buckets keep the fast
    path.

    tim_out: optional .tim path; each archive's TOA lines are APPENDED
    as soon as all its subints are fitted, followed by a completion
    sentinel comment line, so a campaign interrupted mid-run keeps
    every completed archive's results on disk (the fault-tolerance
    analogue of the reference's write-the-model-every-iteration habit,
    ppgauss.py:208-212).

    resume=True RE-ENTERS an interrupted campaign: the checkpoint is
    truncated after its last completion sentinel (dropping the partial
    tail a killed writer left) and archives already recorded complete
    are skipped — only the missing ones are measured, and the final
    .tim holds exactly the uninterrupted run's lines.  skip_archives:
    additional completed set to skip (e.g. archives another worker's
    checkpoint shard already covers, pipeline/ipta.py).  The returned
    summaries cover only the archives measured THIS run; the .tim set
    is the durable cross-run artifact.

    max_inflight: how many fused dispatches may be pending PER DEVICE
    before the host blocks on that device's oldest (the bound is
    exact; None reads config.stream_max_inflight) — dispatch latency,
    archive IO (see prefetch), and device compute all overlap, which
    is what makes campaign-scale throughput dispatch-latency-immune.

    pipeline_depth: how many buckets may occupy a device's two-stage
    copy->fit transfer pipeline at once (None reads
    config.stream_pipeline_depth, default 2).  Depth 2 double-buffers
    the h2d link against in-flight fits — bucket N+1's bytes move
    while bucket N's program runs; depth 1 serializes the stages (the
    A/B arm).  Output — .tim content included — is byte-identical for
    any depth; only the overlap schedule changes.  The h2d_start/
    h2d_done trace events record per-copy bytes, duration, and the
    overlap flag pptrace's link section aggregates.

    stream_devices: which local devices buckets are dealt across,
    round-robin — None reads config.stream_devices; 'auto' = every
    local device of the default backend; an int N = the first N.
    Output (TOA fields and .tim checkpoint content) is digit-identical
    for any device count: results stay keyed by (archive, subint)
    owners and checkpoints are written in archive order.

    telemetry: structured JSONL event trace of the campaign — a path
    (a new trace is written there), a telemetry.Tracer to share (how
    stream_ipta_campaign pools every pulsar into one trace), or None
    to follow config.telemetry_path (default off; PPT_TELEMETRY /
    pptoas --telemetry set it).  Per-bucket dispatch/drain records
    carry device id, shape key, queue depth, and cold-start markers;
    per-archive prepare/flush/skip records and per-TOA quality rollups
    ride along; analyze with tools/pptrace.py.  Tracing reads clocks
    only around already-blocking calls, so enabling it never adds a
    host sync — and output is byte-identical with telemetry on or off.

    quality_flags: add per-TOA -nfev and -chi2 fit diagnostics to the
    TOA flags (.tim lines), sourced from the packed fit results (-snr
    and -gof are always present).  Off by default: golden .tim files
    stay byte-identical.

    Returns a DataBunch with:
      TOA_list        — TOA objects in archive order
      order           — archive paths measured
      DM0s            — per-archive nominal DM (offset-DM reference)
      DeltaDM_means / DeltaDM_errs — per-archive offset-DM statistics
      fit_duration    — total seconds blocked on device dispatches
      scatter_duration — total seconds in host-side result unpack
      nfit            — number of fused dispatches fired
      devices_used    — distinct devices that received dispatches
      peak_inflight   — max pending dispatches observed on one device
      h2d_bytes       — total bytes the copy stages shipped h2d
      h2d_duration    — total seconds the copy stages spent moving
    """
    if isinstance(datafiles, str):
        datafiles = (_read_metafile(datafiles) if _is_metafile(datafiles)
                     else [datafiles])
    else:
        datafiles = list(datafiles)
    tracer, own_tracer = resolve_tracer(telemetry,
                                        run="stream_wideband_TOAs")
    t_start = time.time()

    try:
        # inside the try: a factory/constructor failure (bad options,
        # bad stream_devices, corrupt resume checkpoint) must still
        # close an owned trace
        lane, loader = make_wideband_lane(
            modelfile, nsub_batch=nsub_batch, fit_DM=fit_DM,
            fit_GM=fit_GM, nu_ref_DM=nu_ref_DM, nu_ref_tau=nu_ref_tau,
            DM0=DM0, bary=bary, tscrunch=tscrunch, fit_scat=fit_scat,
            log10_tau=log10_tau, scat_guess=scat_guess,
            fix_alpha=fix_alpha, max_iter=max_iter,
            print_flux=print_flux, print_phase=print_phase,
            instrumental_response_dict=instrumental_response_dict,
            addtnl_toa_flags=addtnl_toa_flags, quiet=quiet,
            quality_flags=quality_flags, tracer=tracer,
            zap_inline=zap_inline, zap_nstd=zap_nstd,
            zap_channels=zap_channels, postfit_cut=postfit_cut)
        ex = _StreamExecutor(lane, datafiles, loader,
                             nsub_batch, max_inflight=max_inflight,
                             prefetch=prefetch, tim_out=tim_out,
                             resume=resume, skip_archives=skip_archives,
                             quiet=quiet, stream_devices=stream_devices,
                             tracer=tracer, pipeline_depth=pipeline_depth)
        meta, assembled = ex.run()
        nfit, fit_duration = ex.nfit, ex.fit_duration

        # ---- collect TOAs + per-archive DeltaDM stats in archive order
        (TOA_list, order, DM0s, DeltaDM_means,
         DeltaDM_errs) = _collect_wideband(meta, assembled)

        tot = time.time() - t_start
        n = len(TOA_list)
        log(f"streamed {n} TOAs from {len(order)} archives in "
            f"{tot:.2f} s ({nfit} fused dispatches across "
            f"{len(ex.devices_used)} device(s), "
            f"{fit_duration:.2f} s blocked on device, "
            f"{ex.scatter_duration:.2f} s in host scatter, "
            f"{n / max(tot, 1e-9):.1f} TOAs/s end-to-end)",
            quiet=quiet, tracer=tracer)
        if tracer.enabled:
            tracer.emit("run_end", driver="stream_wideband_TOAs",
                        n_toas=n, n_archives=len(order), nfit=nfit,
                        peak_inflight=ex.peak_inflight,
                        max_inflight=ex.max_inflight,
                        pipeline_depth=ex.pipeline_depth,
                        fit_s=round(fit_duration, 6),
                        scatter_s=round(ex.scatter_duration, 6),
                        h2d_s=round(ex.h2d_duration, 6),
                        h2d_bytes=int(ex.h2d_bytes),
                        h2d_bytes_logical=int(ex.h2d_logical_bytes),
                        codec_s=round(ex.codec_duration, 6),
                        h2d_overlap_s=round(ex.h2d_overlap_duration, 6),
                        wall_s=round(tot, 6),
                        devices_used=len(ex.devices_used),
                        dispatches_per_device=ex.dispatch_counts)
    finally:
        if own_tracer:
            tracer.close()
    return DataBunch(TOA_list=TOA_list, order=order, DM0s=DM0s,
                     DeltaDM_means=DeltaDM_means,
                     DeltaDM_errs=DeltaDM_errs,
                     fit_duration=fit_duration,
                     scatter_duration=ex.scatter_duration, nfit=nfit,
                     devices_used=len(ex.devices_used),
                     peak_inflight=ex.peak_inflight,
                     h2d_bytes=int(ex.h2d_bytes),
                     h2d_bytes_logical=int(ex.h2d_logical_bytes),
                     codec_duration=ex.codec_duration,
                     h2d_duration=ex.h2d_duration,
                     postfit_zaps=lane.postfit_zaps)


# --------------------------------------------------------------------------
# Narrowband streaming (per-channel 1-D fits at campaign scale)
# --------------------------------------------------------------------------

_NB_KEYS = ("phase", "phase_err", "snr", "gof")
_NB_SCAT_KEYS = _NB_KEYS + ("tau", "tau_err")


def _nb_fit_fields(x, modelx, noise, cmask, freqs, Ps, ft, nbin,
                   fit_scat, log10_tau, tau_mode, max_iter,
                   tau_s=0.0, tau_nu=1.0, tau_a=0.0):
    """Per-channel 1-D fit fields for one narrowband batch (traceable;
    shared by the raw device program and the decoded-fallback dispatch
    so the two lanes cannot drift): fit_phase_shift_batch without
    scattering, else the 5-param engine on flattened single-channel
    portraits with (phi, tau) free (get_narrowband_TOAs' path,
    pipeline/toas.py:786-835)."""
    from ..fit.phase_shift import fit_phase_shift_batch

    nb, nchan = x.shape[0], x.shape[1]
    if not fit_scat:
        r = fit_phase_shift_batch(
            x, jnp.broadcast_to(modelx, x.shape), noise)
        return (r.phase, r.phase_err, r.snr, r.red_chi2)
    flat_x = x.reshape(nb * nchan, 1, nbin)
    flat_m = jnp.broadcast_to(modelx, x.shape).reshape(nb * nchan, 1, nbin)
    flat_noise = noise.reshape(nb * nchan, 1)
    flat_freqs = jnp.broadcast_to(
        freqs, (nb, nchan)).reshape(nb * nchan, 1)
    flat_P = jnp.repeat(Ps, nchan)
    flat_mask = cmask.reshape(nb * nchan, 1)
    if tau_mode == "auto":
        # broadband estimate per subint, scaled per channel with the
        # default index (pipeline/toas.py:802-813)
        tau_sub = estimate_tau_batch(x, modelx, noise, cmask)
        nu_mid = jnp.mean(freqs)
        tau_seed = (tau_sub[:, None] * (freqs[None, :] / nu_mid)
                    ** scattering_alpha).reshape(nb * nchan)
    elif tau_mode == "explicit":
        tau_seed = ((tau_s / flat_P)
                    * (flat_freqs[:, 0] / tau_nu) ** tau_a)
    else:
        tau_seed = jnp.full(nb * nchan, 0.5 / nbin, ft)
    th0 = jnp.zeros((nb * nchan, 5), ft).at[:, 3].set(
        jnp.log10(jnp.maximum(tau_seed, 1e-12)).astype(ft)
        if log10_tau else tau_seed.astype(ft))
    r = fit_portrait_batch(
        flat_x, flat_m, flat_noise, flat_freqs, flat_P,
        flat_freqs[:, 0],
        fit_flags=FitFlags(True, False, False, True, False),
        theta0=th0, chan_masks=flat_mask,
        log10_tau=log10_tau, max_iter=max_iter)
    dof = jnp.maximum(r.dof, 1.0)
    return (r.phi.reshape(nb, nchan), r.phi_err.reshape(nb, nchan),
            r.snr.reshape(nb, nchan), (r.chi2 / dof).reshape(nb, nchan),
            r.tau.reshape(nb, nchan), r.tau_err.reshape(nb, nchan))


@lru_cache(maxsize=None)
def _raw_nb_fn(nchan, nbin, fit_scat, log10_tau, tau_mode, max_iter,
               ftname, redisp, raw_code="i16", pol_sum=False,
               col_scaled=False, zap_nstd=None):
    """ONE jitted program for a narrowband raw bucket: sample decode
    (_raw_decode — shared with the wideband program, so the two lanes
    cannot drift on sample types, sub-byte unpack, column scaling, or
    the pol reduction), baseline,
    optional re-dispersion, then per-channel 1-D fits —
    fit_phase_shift_batch (no scattering) or the 5-param engine with
    (phi, tau) per single-channel portrait (get_narrowband_TOAs'
    flattened path, pipeline/toas.py:786-835).  Returns a packed
    (nfield, nb, nchan) array.

    zap_nstd non-None fuses the inline median noise cut (ISSUE 16
    satellite — the narrowband twin of the wideband raw program's
    ISSUE 12 excision): the iterative cut runs on the device-resident
    noise, the post-zap keep mask zeroes cmask, and one extra packed
    (nb, nchan) 'keep' row tells assembly which per-channel TOAs to
    drop.  The surviving channels' 1-D fits are bit-identical to the
    offline zap-then-fit oracle: each channel's fit reads only its own
    row, so zeroing a NEIGHBOR'S weight cannot perturb it."""
    from ..fit.phase_shift import fit_phase_shift_batch

    ft = {"float32": jnp.float32, "float64": jnp.float64}[ftname]
    tiny = float(np.finfo(ftname).tiny)

    def run(raw, scl, offs, cmask, modelx, freqs, Ps,
            tau_s, tau_nu, tau_a, redisp_turns, tscal=None,
            tzero=None):
        x = _raw_decode(raw, scl, offs, nbin, ft, redisp=redisp,
                        redisp_turns=redisp_turns, code=raw_code,
                        pol_sum=pol_sum,
                        tscal=tscal if col_scaled else None,
                        tzero=tzero if col_scaled else None)
        noise = jnp.maximum(get_noise_PS(x), tiny)
        keep = None
        if zap_nstd is not None:
            from ..quality.excision import zap_keep_mask

            keep, _ = zap_keep_mask(noise, cmask > 0, zap_nstd)
            cmask = cmask * keep.astype(ft)
        fields = _nb_fit_fields(x, modelx, noise, cmask, freqs, Ps,
                                ft, nbin, fit_scat, log10_tau, tau_mode,
                                max_iter, tau_s, tau_nu, tau_a)
        if keep is not None:
            fields = list(fields) + [keep]
        return jnp.stack([jnp.asarray(f).astype(ft) for f in fields])

    return jax.jit(run)


def stream_narrowband_TOAs(datafiles, modelfile, nsub_batch=64,
                           fit_scat=False, log10_tau=True,
                           scat_guess=None, tscrunch=False, max_iter=25,
                           prefetch=True,
                           max_inflight=None, print_phase=False,
                           addtnl_toa_flags={}, tim_out=None,
                           quiet=False, resume=False,
                           skip_archives=None, stream_devices=None,
                           telemetry=None, pipeline_depth=None,
                           zap_inline=False, zap_nstd=None):
    """Campaign-scale narrowband TOAs: per-channel 1-D fits with the
    same raw-int16 device pipeline, bucketing, and asynchronous
    dispatch as stream_wideband_TOAs — one TOA per unzapped channel
    (get_narrowband_TOAs semantics; the reference left the narrowband
    scattering fit "NOT YET IMPLEMENTED", pptoas.py:1046-1049).

    Raw mode covers the full sample-type matrix (sub-byte NBIT packed
    payloads and general TSCAL/TZERO included — see _load_raw); the
    remaining non-raw-representable layouts fall back to a
    host-decoded dispatch of the same device fits.

    zap_inline=True runs the ppzap median noise cut INLINE (ISSUE 16
    satellite — the narrowband twin of stream_wideband_TOAs' ISSUE 12
    excision): raw buckets fuse the iterative median + ``zap_nstd``*std
    cut into the device program and a packed 'keep' row drops the
    flagged channels' TOAs at assembly; decoded-lane archives cut at
    prepare, before the ok-channel lists are derived.  Because every
    narrowband fit is per-channel independent, surviving channels'
    TOAs are BIT-identical to offline-zapping the same lists first —
    the only difference is which channels emit lines.  zap_nstd:
    threshold in stds (None = config.zap_nstd / PPT_ZAP_NSTD).

    tim_out / resume / skip_archives / stream_devices / max_inflight /
    pipeline_depth / telemetry follow stream_wideband_TOAs
    (per-archive completion sentinels; round-robin multi-device
    dispatch through per-device copy->fit transfer pipelines;
    _StreamExecutor; JSONL event tracing).  Returns a
    DataBunch(TOA_list, order, fit_duration, scatter_duration, nfit,
    devices_used, peak_inflight, h2d_bytes, h2d_duration)."""
    if isinstance(datafiles, str):
        datafiles = (_read_metafile(datafiles) if _is_metafile(datafiles)
                     else [datafiles])
    else:
        datafiles = list(datafiles)
    scat_guess = _validate_scat_guess(scat_guess, fit_scat)
    if fit_scat and not log10_tau and scat_guess is None:
        raise ValueError(
            "stream_narrowband_TOAs: log10_tau=False needs scat_guess")
    if not fit_scat:
        log10_tau = False
    model = TemplateModel(modelfile, quiet=quiet)
    p_dependent = model.has_scattering()

    if scat_guess is not None and not isinstance(scat_guess, str):
        tau_mode = "explicit"
        tau_args = tuple(float(v) for v in scat_guess)
    elif fit_scat and scat_guess == "auto":
        tau_mode, tau_args = "auto", (0.0, 1.0, 0.0)
    elif fit_scat:
        tau_mode, tau_args = "neutral", (0.0, 1.0, 0.0)
    else:
        tau_mode, tau_args = "none", (0.0, 1.0, 0.0)

    load_dtype = np.float32 if use_fast_fit_default() else None

    def _loader(f):
        if not tscrunch:  # raw lane cannot time-scrunch on host
            try:
                return _load_raw(f)
            except (ValueError, KeyError):
                pass
        return load_for_toas(f, tscrunch=tscrunch, quiet=True,
                             dtype=load_dtype)

    tracer, own_tracer = resolve_tracer(telemetry,
                                        run="stream_narrowband_TOAs")
    t_start = time.time()
    # inline excision (ISSUE 16 satellite): raw buckets fuse the cut
    # into the device program (an extra packed 'keep' row), decoded
    # buckets cut at prepare — mirroring the wideband lane's split
    from .zap import resolve_zap_device, resolve_zap_nstd

    zap_nstd_run = resolve_zap_nstd(zap_nstd) if zap_inline else None
    keys = _NB_SCAT_KEYS if fit_scat else _NB_KEYS
    if zap_nstd_run is not None:
        # raw buckets append the keep row; decoded buckets' packed
        # stacks are one row shorter and zip() below just ignores the
        # missing key
        keys = keys + ("keep",)
    ftname = "float32" if use_fast_fit_default() else "float64"
    ft = jnp.float32 if use_fast_fit_default() else jnp.float64

    def assemble(m, results):
        """Per-channel TOA objects for one archive."""
        toas = []
        n_cut = 0
        saw_keep = False
        for j, isub in enumerate(m.ok):
            r = results.get((m.iarch, int(isub)))
            if r is None:
                continue
            vals = dict(zip(keys, r))
            saw_keep = saw_keep or "keep" in vals
            P = m.Ps[j]
            for ichan in m.okc[j]:
                if "keep" in vals and not vals["keep"][ichan] > 0:
                    # raw-lane inline zap: the device program flagged
                    # this channel — its TOA line is dropped exactly
                    # as an offline-zapped load would never emit it
                    n_cut += 1
                    continue
                toa_mjd = m.epochs[j].add_seconds(
                    float(vals["phase"][ichan]) * P + m.backend_delay)
                flags = {
                    "be": m.backend, "fe": m.frontend,
                    "f": f"{m.frontend}_{m.backend}",
                    "nbin": int(m.nbin), "subint": int(isub),
                    "chan": int(ichan), "tobs": m.subtimes[j],
                    "tmplt": str(modelfile),
                    "snr": float(vals["snr"][ichan]),
                    "gof": float(vals["gof"][ichan]),
                }
                if fit_scat:
                    flags.update(scat_time_flags(
                        float(vals["tau"][ichan]),
                        float(vals["tau_err"][ichan]), P, log10_tau))
                    flags["scat_ref_freq"] = float(m.freqs0[ichan])
                if print_phase:
                    flags["phs"] = float(vals["phase"][ichan])
                    flags["phs_err"] = float(vals["phase_err"][ichan])
                flags.update(addtnl_toa_flags)
                toas.append(TOA(
                    m.datafile, float(m.freqs0[ichan]), toa_mjd,
                    float(vals["phase_err"][ichan]) * P * 1e6,
                    m.telescope, m.telescope_code, None, None, flags))
        if saw_keep and tracer.enabled:
            # fused raw-lane inline zap (dec archives emitted their
            # events at prepare).  One proposal per raw archive — 0
            # channels for clean data, matching the wideband lane's
            # contract; wall_s is 0 by design: the cut runs inside the
            # fit dispatch.
            tracer.emit("zap_propose", datafile=m.datafile,
                        n_channels=n_cut, n_iter=0, device=True,
                        wall_s=0.0)
            if n_cut:
                tracer.emit("zap_apply", datafile=m.datafile,
                            n_channels=n_cut)
        return toas

    def launch_nb(b, pipeline, seq):
        n = len(b)
        if n == 0:
            return None
        device = pipeline.device
        pad = (-n) % nsub_batch
        idx0 = list(range(n)) + [0] * pad
        # row snapshots on the caller's thread (the bucket is cleared
        # below); the np.stack passes run on the copy worker
        masks_rows = [b.masks[i] for i in idx0]
        Ps = np.asarray([b.Ps[i] for i in idx0])
        t_s, t_nu, t_a = tau_args
        modelx, freqs = b.modelx, b.freqs
        nbin = b.nbin
        if b.kind == "raw":
            rows, dedisp, redisp = _raw_rows(b, idx0)
            col_scaled = b.col_scaled
            tscal_h = (np.asarray([b.tscal[i] for i in idx0])
                       if col_scaled else None)
            tzero_h = (np.asarray([b.tzero[i] for i in idx0])
                       if col_scaled else None)
            fn = _raw_nb_fn(len(np.asarray(freqs)), nbin,
                            bool(fit_scat), bool(log10_tau), tau_mode,
                            int(max_iter), ftname, redisp,
                            raw_code=b.raw_code, pol_sum=b.pol_sum,
                            col_scaled=col_scaled,
                            zap_nstd=zap_nstd_run)

            def copy():
                raw, scl, offs, turns = _stack_rows(rows, dedisp,
                                                    redisp, Ps, freqs)
                masks = np.stack(masks_rows)
                nbytes = [0]
                put = _byte_put(device, nbytes)
                with _on_device(device):
                    tscal_d = put(tscal_h, ft) if col_scaled else None
                    tzero_d = put(tzero_h, ft) if col_scaled else None
                    args = (put(raw), put(scl, ft), put(offs, ft),
                            put(masks, ft), put(modelx, ft),
                            put(freqs, ft), put(Ps, ft),
                            put(turns, ft), tscal_d, tzero_d)
                return args, nbytes[0]

            def fit(raw_d, scl_d, offs_d, masks_d, modelx_d, freqs_d,
                    Ps_d, turns_d, tscal_d, tzero_d):
                with _on_device(device):
                    return fn(raw_d, scl_d, offs_d, masks_d, modelx_d,
                              freqs_d, Ps_d, ft(t_s), ft(t_nu),
                              ft(t_a), turns_d, tscal_d, tzero_d)
        else:
            ports_rows = [b.ports[i] for i in idx0]
            noise_rows = [b.noise[i] for i in idx0]

            def copy():
                ports = np.stack(ports_rows)
                noise = np.stack(noise_rows)
                masks = np.stack(masks_rows)
                nbytes = [0]
                put = _byte_put(device, nbytes)
                with _on_device(device):
                    args = (put(ports, ft), put(modelx, ft),
                            put(noise, ft), put(masks, ft),
                            put(freqs, ft), put(Ps, ft))
                return args, nbytes[0]

            def fit(ports_d, modelx_d, noise_d, masks_d, freqs_d,
                    Ps_d):
                with _on_device(device):
                    return jnp.stack([
                        jnp.asarray(f).astype(ft)
                        for f in _nb_fit_fields(
                            ports_d, modelx_d, noise_d, masks_d,
                            freqs_d, Ps_d, ft, nbin, fit_scat,
                            log10_tau, tau_mode, max_iter, t_s, t_nu,
                            t_a)])

        rec = (pipeline.submit(copy, fit, seq), list(b.owners), None)
        b.clear()
        return rec

    class _NarrowbandLane:
        """stream_narrowband_TOAs' physics hooks for _StreamExecutor."""

        def prepare(self, iarch, datafile, d, ok):
            nchan, nbin = d.nchan, d.nbin
            freqs0 = np.asarray(d.freqs[0], float)
            P_mean = float(np.mean(d.Ps[ok]))
            try:
                modelx = model.portrait(freqs0, nbin, P=P_mean)
            except ValueError as e:
                # typed archive_skip (not just a log line) so pptrace's
                # skipped-archives section shows the REAL mismatch,
                # matching GetTOAs' skip path
                tracer.emit("archive_skip", datafile=datafile,
                            reason=str(e))
                log(f"Skipping {datafile}: {e}", level="warn")
                return None
            raw_mode = bool(d.get("raw_mode", False))
            raw_code = str(d.get("raw_code") or "i16")
            pol_sum = bool(d.get("pol_sum", False))
            col_scaled = raw_mode and (d.get("tscal") is not None
                                       or d.get("tzero") is not None)
            tscal_val = float(d.get("tscal") or 1.0) if raw_mode else 1.0
            tzero_val = float(d.get("tzero") or 0.0) if raw_mode else 0.0
            masks = np.asarray(d.weights[ok] > 0.0, float)
            if zap_nstd_run is not None and not raw_mode and len(ok):
                # decoded-lane inline excision: cut BEFORE the
                # ok-channel lists are derived, so assembly emits
                # exactly the TOA set an offline-zapped load would
                from ..quality.excision import (zap_keep_device,
                                                zap_keep_np)

                noise_z = np.asarray(d.noise_stds[ok, 0])
                use_dev = resolve_zap_device(None)
                t0z = time.perf_counter()
                keep, iters = (zap_keep_device if use_dev
                               else zap_keep_np)(noise_z, masks > 0,
                                                 zap_nstd_run)
                wall_z = time.perf_counter() - t0z
                n_cut = int(masks.sum() - (masks * keep).sum())
                masks = masks * keep
                if tracer.enabled:
                    tracer.emit("zap_propose", datafile=datafile,
                                n_channels=n_cut,
                                n_iter=int(np.max(iters, initial=0)),
                                device=bool(use_dev),
                                wall_s=round(wall_z, 6))
                    if n_cut:
                        tracer.emit("zap_apply", datafile=datafile,
                                    n_channels=n_cut)
            key = (nchan, nbin, freqs0.tobytes(),
                   "raw" if raw_mode else "dec") + (
                       (raw_code, pol_sum, col_scaled)
                       if raw_mode else ()) + (
                       (round(P_mean, 12),) if p_dependent else ())
            m = DataBunch(
                datafile=datafile, iarch=iarch, ok=ok, nbin=nbin,
                freqs0=freqs0,
                okc=[np.flatnonzero(masks[j] > 0)
                     for j in range(len(ok))],
                epochs=[d.epochs[isub] for isub in ok],
                Ps=[float(d.Ps[isub]) for isub in ok],
                subtimes=[float(d.subtimes[isub]) for isub in ok],
                backend_delay=d.backend_delay, backend=d.backend,
                frontend=d.frontend, telescope=d.telescope,
                telescope_code=d.telescope_code)

            def factory(freqs0=freqs0, nbin=nbin, modelx=modelx,
                        raw_mode=raw_mode, raw_code=raw_code,
                        pol_sum=pol_sum, col_scaled=col_scaled):
                return _Bucket(freqs0, nbin, modelx, (),
                               kind="raw" if raw_mode else "dec",
                               raw_code=raw_code, pol_sum=pol_sum,
                               col_scaled=col_scaled)

            per_subint = []
            for j, isub in enumerate(ok):

                def fill(b, j=j, isub=int(isub), d=d, masks=masks,
                         raw_mode=raw_mode, iarch=iarch,
                         col_scaled=col_scaled, tscal_val=tscal_val,
                         tzero_val=tzero_val):
                    if raw_mode:
                        b.raw.append(d.raw[isub])
                        b.scl.append(d.scl[isub])
                        b.offs.append(d.offs[isub])
                        if col_scaled:
                            b.tscal.append(tscal_val)
                            b.tzero.append(tzero_val)
                        # reference frequency honors the REF_FREQ card
                        b.dedisp.append(
                            (float(d.DM) if d.get("dmc") else 0.0,
                             float(d.get("dedisp_nu")
                                   or d.get("nu0", 0.0) or 0.0)))
                    else:
                        b.ports.append(np.asarray(d.subints[isub, 0]))
                        b.noise.append(
                            np.asarray(d.noise_stds[isub, 0], float))
                    b.masks.append(masks[j])
                    b.Ps.append(float(d.Ps[isub]))
                    b.owners.append((iarch, isub))

                per_subint.append((key, factory, fill))
            return m, per_subint

        def launch(self, b, pipeline, seq):
            return launch_nb(b, pipeline, seq)

        def scatter(self, out, owners, extra, results):
            packed = np.asarray(out)
            for i, owner in enumerate(owners):
                results[owner] = packed[:, i]  # (nfield, nchan)

        def assemble(self, m, results):
            return (assemble(m, results),)

    try:
        # inside the try: a constructor failure (bad stream_devices,
        # corrupt resume checkpoint) must still close an owned trace
        ex = _StreamExecutor(_NarrowbandLane(), datafiles, _loader,
                             nsub_batch, max_inflight=max_inflight,
                             prefetch=prefetch, tim_out=tim_out,
                             resume=resume, skip_archives=skip_archives,
                             quiet=quiet, stream_devices=stream_devices,
                             tracer=tracer, pipeline_depth=pipeline_depth)
        meta, assembled = ex.run()
        nfit, fit_duration = ex.nfit, ex.fit_duration

        # ---- collect per-archive TOAs in archive order ---------------
        TOA_list, order = [], []
        for m in meta:
            TOA_list.extend(assembled[m.iarch][0])
            order.append(m.datafile)

        tot = time.time() - t_start
        n = len(TOA_list)
        log(f"streamed {n} narrowband TOAs from {len(order)} archives "
            f"in {tot:.2f} s ({nfit} fused dispatches across "
            f"{len(ex.devices_used)} device(s), "
            f"{fit_duration:.2f} s blocked on device, "
            f"{ex.scatter_duration:.2f} s in host scatter, "
            f"{n / max(tot, 1e-9):.1f} TOAs/s end-to-end)",
            quiet=quiet, tracer=tracer)
        if tracer.enabled:
            tracer.emit("run_end", driver="stream_narrowband_TOAs",
                        n_toas=n, n_archives=len(order), nfit=nfit,
                        peak_inflight=ex.peak_inflight,
                        max_inflight=ex.max_inflight,
                        pipeline_depth=ex.pipeline_depth,
                        fit_s=round(fit_duration, 6),
                        scatter_s=round(ex.scatter_duration, 6),
                        h2d_s=round(ex.h2d_duration, 6),
                        h2d_bytes=int(ex.h2d_bytes),
                        h2d_bytes_logical=int(ex.h2d_logical_bytes),
                        codec_s=round(ex.codec_duration, 6),
                        h2d_overlap_s=round(ex.h2d_overlap_duration, 6),
                        wall_s=round(tot, 6),
                        devices_used=len(ex.devices_used),
                        dispatches_per_device=ex.dispatch_counts)
    finally:
        if own_tracer:
            tracer.close()
    return DataBunch(TOA_list=TOA_list, order=order,
                     fit_duration=fit_duration,
                     scatter_duration=ex.scatter_duration, nfit=nfit,
                     devices_used=len(ex.devices_used),
                     peak_inflight=ex.peak_inflight,
                     h2d_bytes=int(ex.h2d_bytes),
                     h2d_bytes_logical=int(ex.h2d_logical_bytes),
                     h2d_duration=ex.h2d_duration)
