"""Read IPTA/tempo2-style .tim files (the format io/tim.py writes).

Line grammar (reference write_TOAs, pplib.py:3588-3649):
  archive freq MJDint.MJDfrac err_us site -flag value ...
with the wideband DM carried in ``-pp_dm`` / ``-pp_dme`` flags and the
TEMPO2 convention that 0.0 MHz means infinite frequency
(pplib.py:3613).  The MJD is split digit-exactly into (int day,
float64 fractional day) — parsing it as one float64 would cost ~us of
timing precision.
"""

from dataclasses import dataclass, field

__all__ = ["TimTOA", "read_tim"]


@dataclass
class TimTOA:
    archive: str
    frequency: float          # MHz; inf for the 0.0 convention
    mjd_int: int
    mjd_frac: float           # [0, 1) day, full f64 precision
    error_us: float
    site: str
    dm: float = None          # -pp_dm  [pc cm^-3]
    dm_err: float = None      # -pp_dme
    flags: dict = field(default_factory=dict)

    @property
    def mjd(self):
        """Approximate (single-f64) MJD — display/grouping only."""
        return self.mjd_int + self.mjd_frac


def read_tim(path_or_lines):
    """Parse a .tim file (or iterable of lines) into a list of TimTOA.

    Skips comments (#, C), blank lines, and directives (FORMAT, MODE,
    EFAC-style lines with fewer than 5 leading data columns)."""
    if isinstance(path_or_lines, str):
        with open(path_or_lines) as f:
            lines = f.readlines()
    else:
        lines = list(path_or_lines)
    toas = []
    for line in lines:
        s = line.strip()
        if not s or s.startswith("#") or s.startswith("C "):
            continue
        parts = s.split()
        if len(parts) < 5 or parts[0].upper() in ("FORMAT", "MODE",
                                                  "EFAC", "EQUAD",
                                                  "TIME", "JUMP"):
            continue
        try:
            freq = float(parts[1])
            mjd_s = parts[2]
            err = float(parts[3])
        except ValueError:
            continue
        if "." in mjd_s:
            day_s, frac_s = mjd_s.split(".", 1)
            mjd_int = int(day_s)
            mjd_frac = float("0." + frac_s)
        else:
            mjd_int, mjd_frac = int(mjd_s), 0.0
        flags = {}
        i = 5
        while i < len(parts):
            if parts[i].startswith("-") and i + 1 < len(parts):
                flags[parts[i][1:]] = parts[i + 1]
                i += 2
            else:
                i += 1
        dm = flags.get("pp_dm")
        dm_err = flags.get("pp_dme")
        toas.append(TimTOA(
            archive=parts[0],
            frequency=float("inf") if freq == 0.0 else freq,
            mjd_int=mjd_int, mjd_frac=mjd_frac, error_us=err,
            site=parts[4],
            dm=float(dm) if dm is not None else None,
            dm_err=float(dm_err) if dm_err is not None else None,
            flags=flags))
    return toas
