"""Incremental wideband GLS: sequential rank-update timing for the
online ingest lane (ISSUE 18).

The batch fit (timing/gls.py) rebuilds and re-solves the whole
campaign's whitened system on every call — O(n p^2) per update once a
watch folder is appending TOAs one archive at a time.  This module
keeps the UN-NORMALIZED normal equations (M = A^T A, b = A^T r)
resident and folds each new wideband TOA in as a rank-2 update (one
whitened arrival-time row + one whitened DM row), then reproduces
``gls_solve_np``'s exact algorithm from the accumulated quantities:
the column norms it normalizes by are sqrt(diag(M)), so the
column-normalized normal matrix, the pseudoinverse, the covariance
and the parameter vector all come out of M and b alone — O(p^2)
memory and O(p^3) solve per update, independent of campaign length.

Two structural events break the pure rank-update picture and are
handled explicitly:

* DMX COLUMN GROWTH — a new observing epoch adds a (time + DM) design
  column.  Old rows have exactly zero in the new column, so M and b
  grow by a zero row/column and the update proceeds; nothing is
  recomputed.
* EPOCH RENUMBERING — a TOA arriving out of time order can change the
  epoch assignment of PREVIOUS TOAs (``_group_epochs`` is defined on
  the sorted MJDs).  That invalidates the accumulated columns, so the
  lane detects it and rebuilds from ``build_gls_system`` (a structural
  resolve), keeping correctness for arbitrary arrival order.

The batch solver stays the DIGIT ORACLE: every ``resolve_every``
updates (config.gls_resolve_every) the lane rebuilds the full system,
compares solutions, and REFUSES loudly (``GLSDriftError``) if the
incremental parameters drifted beyond ``drift_tol`` — float
accumulation is not allowed to rot silently.  The resolve also
re-anchors the accumulated state to the batch system, so drift can
never compound across resolve windows.
"""

import numpy as np

from .. import config
from .gls import build_gls_system, finalize_gls, gls_solve_np
from ..config import Dconst
from . import binary as _binary

__all__ = ["IncrementalGLS", "GLSDriftError"]

SECPERDAY = 86400.0


class GLSDriftError(ValueError):
    """The incremental solution drifted from the batch oracle beyond
    tolerance at a periodic resolve — the accumulated normal equations
    are no longer trustworthy and the caller must restart the lane
    (or investigate the campaign: a drift this large usually means the
    system turned ill-conditioned, not that float addition failed)."""


def _solve_from_normal(M, b):
    """``gls_solve_np`` reproduced from the accumulated normal
    equations: col_j = sqrt(M_jj) is exactly sqrt((A**2).sum(axis=0)),
    so the normalized normal matrix is M / (col col^T) and the
    normalized RHS is b / col."""
    col = np.sqrt(np.maximum(np.diag(M), 0.0))
    col = np.where(col > 0, col, 1.0)
    Mn = (M / col[:, None]) / col[None, :]
    bn = b / col
    N = np.linalg.pinv(Mn)
    xn = N @ bn
    x = xn / col
    cov = (N / col[:, None]) / col[None, :]
    perr = np.sqrt(np.maximum(np.diag(cov), 0.0))
    return x, perr, cov


class IncrementalGLS:
    """Sequential wideband GLS over a growing TOA stream.

    >>> lane = IncrementalGLS(par)
    >>> for toa in stream:          # timing.tim.TimTOA
    ...     result = lane.update(toa)   # WidebandGLSResult or None
    ``update`` returns None until two usable TOAs have arrived (the
    batch fit's own minimum); after that every call returns the
    current full WidebandGLSResult, digit-comparable to running
    ``wideband_gls_fit`` on the TOAs seen so far.

    resolve_every: full batch resolves + drift gate every N updates
    (default config.gls_resolve_every; 0 disables the periodic gate —
    structural resolves on epoch renumbering still happen).
    drift_tol: max |x_inc - x_batch| (absolute + relative) tolerated
    at a resolve before GLSDriftError.
    tracer: optional telemetry.Tracer; resolves bump the
    'incremental_resolves' counter the pptrace summary reports.
    """

    def __init__(self, par, fit_f0=True, fit_f1=False, fit_binary=True,
                 epoch_gap_days=0.5, resolve_every=None,
                 drift_tol=1e-10, allow_wraps=False, tracer=None):
        self.par = par
        self.fit_f0 = fit_f0
        self.fit_f1 = fit_f1
        self.fit_binary = fit_binary
        self.epoch_gap_days = float(epoch_gap_days)
        self.resolve_every = (config.gls_resolve_every
                              if resolve_every is None
                              else int(resolve_every))
        self.drift_tol = float(drift_tol)
        self.allow_wraps = allow_wraps
        self.tracer = tracer
        self.n_updates = 0
        self.n_resolves = 0

        # par-derived constants, validated exactly like the batch fit
        # (build_gls_system refuses unmodeled binary keys etc.; run a
        # cheap dry parse now so a bad par fails at construction, not
        # at the 2nd TOA)
        def fget(key, default=None):
            v = par.get(key, default)
            return (float(str(v).replace("D", "E"))
                    if v is not None else None)

        if fget("PEPOCH") is None:
            raise ValueError(
                "IncrementalGLS: parfile is missing PEPOCH")
        if fget("F0") is None and fget("P0") is None:
            raise ValueError(
                "IncrementalGLS: parfile has neither F0 nor P0")
        self._PEPOCH = fget("PEPOCH")
        self._DM0 = fget("DM", 0.0)
        from ..utils.spin import spin_F0

        self._F0r = spin_F0(par)
        self._F0 = float(self._F0r)
        self._bp = (_binary.parse_binary(par)
                    if hasattr(par, "get") else None)

        self._toas = []          # usable TOAs, arrival order
        self._n_dropped = 0
        self._names = None       # global column names (fixed)
        self._nep = 0
        self._M = None           # (p, p) accumulated A^T A
        self._b = None           # (p,) accumulated A^T r
        self._rows_t = []        # whitened time rows (len p_at_birth)
        self._rows_d = []        # whitened DM rows
        self._r_w = []           # (r_t_w, r_d_w) per TOA
        self._solution = None    # latest WidebandGLSResult

    # ------------------------------------------------------------------
    def _toa_row(self, toa, epoch, nep):
        """One TOA's whitened (time row, DM row, r_t, r_d) exactly as
        ``build_gls_system`` constructs them — same column order, same
        exact-rational phase reduction."""
        from ..utils.spin import day_phase_frac

        freq = float(toa.frequency)
        mjd_i = np.int64(toa.mjd_int)
        mjd_f = float(toa.mjd_frac)
        sig_t = float(toa.error_us) * 1e-6
        dm_err = float(toa.dm_err)

        delay_s = 0.0
        dparts = None
        if self._bp is not None:
            d, parts = _binary.binary_delay_and_partials(
                self._bp, np.array([mjd_i]), np.array([mjd_f]))
            delay_s = float(np.asarray(d, np.float64)[0])
            dparts = np.asarray(parts, np.float64)[:, 0]

        finite = np.isfinite(freq)
        disp_s = Dconst * self._DM0 * freq ** -2.0 if finite else 0.0
        pep = self._PEPOCH
        dt_s = ((int(mjd_i) - int(pep)) * SECPERDAY
                + (mjd_f - (pep - int(pep))) * SECPERDAY
                - disp_s - delay_s)

        phase = (day_phase_frac(self._F0r, int(pep), int(mjd_i))
                 + self._F0 * ((mjd_f - (pep - int(pep))) * SECPERDAY
                               - disp_s - delay_s))
        dphase = phase - np.round(phase)
        r_t = dphase / self._F0

        cols = {"OFFSET": 1.0}
        if self.fit_f0:
            cols["F0"] = -dt_s / self._F0
        if self.fit_f1:
            cols["F1"] = -0.5 * dt_s ** 2.0 / self._F0
        if self._bp is not None and self.fit_binary:
            for name, v in zip(self._bp.param_names, dparts):
                cols[name] = float(v)
        names = list(cols)
        nglob = len(names)
        row_t = np.zeros(nglob + nep)
        for j, k in enumerate(names):
            row_t[j] = cols[k]
        if finite:
            row_t[nglob + epoch] = Dconst * freq ** -2.0
        row_d = np.zeros(nglob + nep)
        row_d[nglob + epoch] = 1.0
        r_d = float(toa.dm) - self._DM0

        return (names, row_t / sig_t, row_d / dm_err,
                r_t / sig_t, r_d / dm_err, sig_t, dm_err, r_t, r_d)

    def _rebuild(self):
        """Structural resolve: rebuild the accumulated state from the
        batch system (epoch renumbering, or re-anchoring after a
        periodic resolve)."""
        system = build_gls_system(
            self._toas, self.par, fit_f0=self.fit_f0,
            fit_f1=self.fit_f1, fit_binary=self.fit_binary,
            epoch_gap_days=self.epoch_gap_days,
            allow_wraps=self.allow_wraps)
        n = system.n
        self._names = list(system.names)
        self._nep = int(system.nep)
        A, r = system.A, system.r
        self._M = A.T @ A
        self._b = A.T @ r
        self._rows_t = [A[i].copy() for i in range(n)]
        self._rows_d = [A[n + i].copy() for i in range(n)]
        self._r_w = [(float(r[i]), float(r[n + i])) for i in range(n)]
        return system

    def _epochs(self, mjds):
        from .gls import _group_epochs

        return _group_epochs(np.asarray(mjds), self.epoch_gap_days)

    def _system_bunch(self):
        """A build_gls_system-shaped bunch assembled from the resident
        state, for finalize_gls."""
        from ..utils.bunch import DataBunch

        n = len(self._toas)
        mjds = [t.mjd_int + t.mjd_frac for t in self._toas]
        epochs = self._epochs(mjds)
        p = len(self._names) + self._nep
        A = np.zeros((2 * n, p))
        r = np.zeros(2 * n)
        for i in range(n):
            A[i, :len(self._rows_t[i])] = self._rows_t[i]
            A[n + i, :len(self._rows_d[i])] = self._rows_d[i]
            r[i], r[n + i] = self._r_w[i]
        sig_t = np.array([t.error_us * 1e-6 for t in self._toas])
        dm_errs = np.array([t.dm_err for t in self._toas])
        return DataBunch(
            A=A, r=r, names=self._names, nep=self._nep, epochs=epochs,
            sig_t=sig_t, dm_errs=dm_errs,
            errs_us=np.array([t.error_us for t in self._toas]),
            r_t=r[:n] * sig_t, r_d=r[n:] * dm_errs, n=n,
            n_dropped=self._n_dropped, binary=self._bp)

    # ------------------------------------------------------------------
    def update(self, toa):
        """Fold one TimTOA into the solution.  Returns the current
        WidebandGLSResult (None until >= 2 usable TOAs)."""
        if toa.dm is None or not toa.dm_err:
            self._n_dropped += 1
            return self._solution
        self._toas.append(toa)
        n = len(self._toas)
        if n < 2:
            return None
        self.n_updates += 1

        mjds = [t.mjd_int + t.mjd_frac for t in self._toas]
        epochs = self._epochs(mjds)
        structural = (
            self._M is None
            or len(self._rows_t) != n - 1
            or not np.array_equal(
                self._epochs(mjds[:-1]), epochs[:-1]))
        if structural:
            # first solvable update, or epoch renumbering: batch build
            self._rebuild()
        else:
            epoch = int(epochs[-1])
            if epoch >= self._nep:
                # DMX column growth: old rows are exactly zero in the
                # new column, so M/b grow by a zero row/column
                grow = epoch + 1 - self._nep
                self._M = np.pad(self._M, ((0, grow), (0, grow)))
                self._b = np.pad(self._b, (0, grow))
                self._nep = epoch + 1
            (_names, a_t, a_d, rt_w, rd_w, _sig, _dme, _rt, _rd) = \
                self._toa_row(toa, epoch, self._nep)
            self._M += np.outer(a_t, a_t) + np.outer(a_d, a_d)
            self._b += a_t * rt_w + a_d * rd_w
            self._rows_t.append(a_t)
            self._rows_d.append(a_d)
            self._r_w.append((rt_w, rd_w))

        x, perr, _cov = _solve_from_normal(self._M, self._b)
        system = self._system_bunch()
        # gls_solve_np's post = r - An @ xn == r - A @ x up to
        # normalization round-off; the raw form is the same math
        post = system.r - system.A @ x
        chi2 = float((post ** 2.0).sum())
        self._solution = finalize_gls(system, x, perr, post, chi2)

        if self.resolve_every and \
                self.n_updates % self.resolve_every == 0:
            self.resolve()
        return self._solution

    def resolve(self):
        """Full batch resolve: rebuild the system, gate incremental
        drift against the oracle, re-anchor the accumulated state.
        Returns the batch WidebandGLSResult."""
        x_inc = None
        if self._M is not None:
            x_inc, _, _ = _solve_from_normal(self._M, self._b)
        system = self._rebuild()
        x, perr, _cov, post, chi2 = gls_solve_np(system.A, system.r)
        self.n_resolves += 1
        if self.tracer is not None and self.tracer.enabled:
            self.tracer.counter("incremental_resolves")
        if x_inc is not None and len(x_inc) == len(x):
            scale = np.maximum(1.0, np.abs(x))
            drift = float(np.max(np.abs(x_inc - x) / scale))
            if drift > self.drift_tol:
                raise GLSDriftError(
                    f"incremental GLS drifted {drift:.3e} from the "
                    f"batch oracle after {self.n_updates} update(s) "
                    f"(tolerance {self.drift_tol:.1e}) — the "
                    "accumulated normal equations are not "
                    "trustworthy; restart the lane")
        self._solution = finalize_gls(system, x, perr, post, chi2)
        return self._solution

    @property
    def result(self):
        """Latest WidebandGLSResult (None before 2 usable TOAs)."""
        return self._solution
