"""Scattering kernels.

A thin-screen scattered pulse is the intrinsic profile convolved with a
one-sided exponential of timescale tau; the timescale follows a power
law in frequency, tau_n = tau * (nu_n / nu_tau)**alpha (alpha ~ -4).

Everything here works in the Fourier (harmonic) domain with tau in
*rotations* (phase units); conversions from seconds happen at the I/O
boundary (tau_rot = tau_sec / P).

The analytic FT of the unit-area one-sided exponential is
    H(k) = 1 / (1 + 2*pi*i * k * tau),
(reference pplib.py:4219-4242 uses the same form with tau in bins).
The reference's hand-derived dH/dtau and d2H/dtau2 chains
(pptoaslib.py:266-418) are replaced by jax.grad through this function.
"""

import jax
import jax.numpy as jnp
from .fourier import irfft_c, rfft_c


def scattering_times(tau, alpha, freqs, nu_tau):
    """Per-channel scattering timescales tau_n = tau*(nu_n/nu_tau)**alpha.

    Units of tau are preserved (rotations in the fit engines).
    Parity: reference pplib.py:4212-4216.
    """
    return tau * (freqs / nu_tau) ** alpha


def scattering_profile_FT(tau, nharm):
    """FT of the unit-area one-sided exponential exp(-t/tau)/tau, t>=0,
    at integer harmonics k = 0..nharm-1; tau in rotations.

    tau = 0 gives the identity kernel (no scattering).
    Parity: reference pplib.py:4219-4242.
    """
    k = jnp.arange(nharm, dtype=jnp.result_type(tau, jnp.float32))
    t = 2.0 * jnp.pi * k * tau
    return 1.0 / jax.lax.complex(jnp.ones_like(t), t)


def scattering_profile_FT_dtau(tau, nharm):
    """Analytic dH/dtau of scattering_profile_FT:
    H = 1/(1 + 2 pi i k tau) => dH/dtau = -2 pi i k H^2 — the
    closed-form companion the LM template engine's analytic Jacobian
    uses (ISSUE 14; the reference's hand-derived chain,
    pptoaslib.py:266-418, restored as an op instead of jax.grad)."""
    k = jnp.arange(nharm, dtype=jnp.result_type(tau, jnp.float32))
    H = scattering_profile_FT(tau, nharm)
    two_pi_k = 2.0 * jnp.pi * k
    return jax.lax.complex(jnp.zeros_like(two_pi_k), -two_pi_k) * H * H


def scattering_portrait_FT_dtau(taus, nharm):
    """Per-channel dH/dtau_n of scattering_portrait_FT; taus
    (..., nchan) -> (..., nchan, nharm) complex (same broadcast shape
    as the forward op)."""
    k = jnp.arange(nharm, dtype=jnp.result_type(taus, jnp.float32))
    H = scattering_portrait_FT(taus, nharm)
    two_pi_k = 2.0 * jnp.pi * k
    return jax.lax.complex(jnp.zeros_like(two_pi_k), -two_pi_k) * H * H


def scattering_portrait_FT(taus, nharm):
    """Per-channel scattering kernels; taus (..., nchan) in rotations ->
    (..., nchan, nharm) complex.

    Parity: reference pplib.py:4245-4260 (which loops channels in
    Python; here it is one broadcast op).
    """
    k = jnp.arange(nharm, dtype=jnp.result_type(taus, jnp.float32))
    t = 2.0 * jnp.pi * taus[..., None] * k
    return 1.0 / jax.lax.complex(jnp.ones_like(t), t)


def scattering_kernel_time(tau, nbin, dtype=jnp.float64):
    """Time-domain one-sided exponential kernel over one rotation,
    normalized to unit sum; tau in rotations.  tau <= 0 gives a delta.

    Used by the synthetic generator; parity: reference pplib.py:1140-1161.
    """
    t = jnp.arange(nbin, dtype=dtype) / nbin
    kern = jnp.where(tau > 0.0, jnp.exp(-t / jnp.where(tau > 0.0, tau, 1.0)), 0.0)
    delta = jnp.zeros(nbin, dtype).at[0].set(1.0)
    kern = jnp.where(tau > 0.0, kern, delta)
    return kern / jnp.sum(kern)


def add_scattering(port, taus, wrap=True):
    """Circularly convolve each channel of a (…, nchan, nbin) portrait
    with its one-sided exponential kernel (taus in rotations).

    The reference uses a repeat-3 linear-convolution trick
    (pplib.py:1164-1187) to approximate non-wrapped scattering; with
    ``wrap`` (default) we convolve circularly via the analytic FT,
    which matches the Fourier-domain model used in the fits exactly.
    """
    port = jnp.asarray(port)
    nbin = port.shape[-1]
    pFT = rfft_c(port)
    H = scattering_portrait_FT(jnp.asarray(taus), pFT.shape[-1])
    return irfft_c(pFT * H, n=nbin)
