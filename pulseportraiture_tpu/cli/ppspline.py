"""ppspline — build a PCA + B-spline model.

Flag parity: reference ppspline.py:291-397 (default norm 'prof').
"""

import argparse
import sys


def build_parser():
    p = argparse.ArgumentParser(
        prog="ppspline", description=__doc__.splitlines()[0])
    p.add_argument("-d", "--datafile", required=True,
                   help="PSRFITS archive (an averaged portrait).")
    p.add_argument("-o", "--modelfile", default=None,
                   help="Output model file name. [default=<datafile>.spl]")
    p.add_argument("-l", "--model_name", default=None)
    p.add_argument("-a", "--archive", default=None,
                   help="Also write the model reconstruction as a PSRFITS "
                        "archive with this name.")
    p.add_argument("-N", "--norm", default="prof",
                   choices=("None", "mean", "max", "prof", "rms", "abs"))
    p.add_argument("-s", "--smooth", action="store_true", default=False,
                   help="Wavelet-smooth the eigenvectors and mean.")
    p.add_argument("-n", "--max_ncomp", type=int, default=10)
    p.add_argument("-S", "--snr", dest="snr_cutoff", type=float,
                   default=150.0)
    p.add_argument("-T", "--rchi2_tol", type=float, default=0.1)
    p.add_argument("-k", "--degree", dest="k", type=int, default=3)
    p.add_argument("-f", "--sfac", type=float, default=1.0)
    p.add_argument("-t", "--knots", dest="max_nbreak", type=int,
                   default=None)
    p.add_argument("--plots", dest="make_plots", action="store_true",
                   default=False,
                   help="Save eigenprofile and spline-projection plots.")
    p.add_argument("--gauss-device", default=None,
                   help="With -s/--smooth: smooth the MEAN profile by "
                        "a Gaussian-component LM fit (the template "
                        "factory's lane) instead of wavelets, on the "
                        "'off' (host-serial) | 'auto' | 'on' (batched) "
                        "engine; eigenprofiles keep wavelet smoothing. "
                        "[default: wavelet mean smoothing]")
    p.add_argument("--quiet", action="store_true", default=False)
    return p


def main(argv=None):
    parser = build_parser()
    args = parser.parse_args(argv)
    gauss_device = None
    if args.gauss_device is not None:
        from .ppfactory import parse_gauss_device

        gauss_device = parse_gauss_device(args.gauss_device)
        if not args.smooth:
            # fail LOUDLY rather than silently running no smoothing at
            # all — the flag selects the MEAN-smoothing lane, which
            # only exists under -s/--smooth
            raise SystemExit("--gauss-device requires -s/--smooth "
                             "(it selects the lane that smooths the "
                             "mean profile)")
    from ..pipeline.spline import SplinePortrait

    dp = SplinePortrait(args.datafile, quiet=args.quiet)
    if args.norm and args.norm != "None":
        dp.normalize_portrait(args.norm)
    smooth_mean = None
    if args.gauss_device is not None and args.smooth:
        from ..pipeline.factory import gauss_smooth_mean

        smooth_mean = gauss_smooth_mean(dp, rchi2_tol=args.rchi2_tol,
                                        gauss_device=gauss_device)
    dp.make_spline_model(
        max_ncomp=args.max_ncomp, smooth=args.smooth,
        snr_cutoff=args.snr_cutoff, rchi2_tol=args.rchi2_tol, k=args.k,
        sfac=args.sfac, max_nbreak=args.max_nbreak,
        model_name=args.model_name, smooth_mean_prof=smooth_mean,
        quiet=args.quiet)
    outfile = args.modelfile or (args.datafile + ".spl")
    dp.write_model(outfile, quiet=args.quiet)
    if args.archive:
        dp.write_model_archive(args.archive, quiet=args.quiet)
    if args.make_plots:
        dp.show_eigenprofiles(show=False,
                              savefig=outfile + ".eigen.png")
        if dp.ncomp:
            # writes <outfile>.proj.png and <outfile>.freq.png
            # (reference ppspline savefig-substring convention)
            dp.show_spline_curve_projections(show=False,
                                             savefig=outfile)
    return 0


if __name__ == "__main__":
    sys.exit(main())
